"""Minimal repro: XLA:TPU convert+reduce fusion pathology (~11 GB/s).

Context (BASELINE.md "Round-4 AlexNet deep-dive"): in the AlexNet
training step the conv1/conv2 bias-gradient — a relu-derivative mask
on the bf16 error flow followed by an f32-accumulating reduction over
batch*space — lowers to a `convert_reduce` loop fusion that runs at
~11 GB/s effective HBM bandwidth on a v5e (chip roofline ~800 GB/s),
costing 19.5 + 11.1 ms of a 284 ms step (~3.5%). Four semantically
equivalent rewrites measured end-to-end were all SLOWER (the notes in
veles/znicz_tpu/ops/gd_conv.py:122), so the production code keeps the
cleanest form and this file records the standalone evidence for an
upstream XLA escalation (VERDICT r4 directive #7).

Run on a TPU: ``python docs/repro_convert_reduce.py``. It times the
isolated bias-grad computation at the AlexNet conv1/conv2 shapes in
four variants and prints effective bandwidth for each, then dumps the
optimized HLO of the pathological one to
``/tmp/convert_reduce_repro_hlo.txt``. Timing uses the repo's tunnel-
safe methodology: data-dependent `lax.scan` chaining (independent
identical dispatches get CSE'd), scalar readback as the sync point,
and a two-rep-count difference to cancel the ~100 ms tunnel
round-trip (BASELINE.md "Timing methodology correction").

MEASURED OUTCOME (v5e behind the dev tunnel, 2026-07-31, the 120-vs-
12-rep unrolled run recorded below): the pathology does NOT reproduce
standalone. Isolated, the production form runs at 250 GB/s effective
on the conv1 shape and 179 GB/s on conv2 (0.59 / 0.53 ms) — 16-23x
the ~11 GB/s the SAME computation shows inside the AlexNet program
(round-4 trace: 19.5 + 11.1 ms; A/B with bias grads zeroed recovers
~21 ms of loop fusion) — and a matmul stand-in for the wgrad consumer
shows ZERO marginal bias-reduce cost (ctx_full − ctx_nobias =
−0.01 / +0.05 ms). CONCLUSION for the upstream report: this is a
fusion-DECISION defect specific to the conv-consumer context — XLA
duplicates the masked-convert producer into the bias-reduce fusion
next to the conv consumers — not a reduce-codegen defect; the
reproducer is the full program (bench_alexnet.py), and
``docs/convert_reduce_fusion_hlo.txt`` carries the offending fusion
computations extracted from its optimized HLO. (Environment notes:
wrapping the ctx variants in a long ``lax.scan`` chain stalled the
tunnel's remote-compile service indefinitely — the unrolled timing
form below is what produced the numbers — and sub-ms variants like
the bare f32_reduce still read unphysical rates through the tunnel's
dispatch jitter; only the >=0.2 ms rows are trustworthy.)

Variant definitions:

* `mask_matvec`  — dz = err * (y > 0); ones @ dz (f32 accumulate):
  the production form; in-graph it fuses mask+convert+reduce.
* `mask_sum`     — dz.sum(axis=0) instead of the matvec.
* `pre_masked`   — the matvec on an ALREADY-masked f32 dz (isolates
  the reduction from the convert+mask producer).
* `f32_reduce`   — plain f32 sum at the same element count (the
  bandwidth baseline XLA should be hitting).
* `ctx` / `ctx_nobias` — dz additionally feeding a wgrad-style
  contraction (the real program's consumer structure); the bias
  reduce's MARGINAL cost is ctx − ctx_nobias. The round-4 in-program
  trace showed the pathology only materializes in this multi-consumer
  context (XLA duplicates the mask+convert producer into the reduce
  fusion instead of reusing the conv's operand), so the isolated
  variants above are the control group: if they run at roofline while
  the marginal in-context cost is ~milliseconds, the fusion-duplication
  decision — not the reduce codegen itself — is the bug.
* `kernel` — the SHIPPED fix (ISSUE 14): the hand-fused Pallas
  bias-grad kernel (``veles/znicz_tpu/ops/pallas_grads.py``) doing
  mask + convert + f32 block-reduce in one sequential-grid pass. It
  is wired into ``gd.py``/``gd_conv.py`` behind the
  ``fused_bias_grad`` escape hatch (on real TPUs when
  $VELES_FUSED_BIAS_GRAD=1; opt-in until the device window below
  fills the table), so the
  training program no longer CONTAINS a bias reduce for XLA's fusion
  pass to duplicate the producer into — the decision this file
  documents is sidestepped, not re-litigated.
* `ctx_kernel` — the kernel inside the multi-consumer context (dz
  still feeds the wgrad contraction): ``ctx_kernel − ctx_nobias`` is
  the shipped form's marginal bias-reduce cost, the number to hold
  against the pathological ``ctx − ctx_nobias``.

PALLAS-KERNEL OUTCOME (ISSUE 14): exactness is pinned on CPU
interpret mode (``tests/test_pallas_grads.py``, atol at the existing
gd bounds) and the bench ledger tracks ``bias_grad_step_seconds``
per round. The measured IN-PROGRAM step delta on a real v5e is
PENDING the next TPU window — this container has no device (the r05
bench also died in device init) — so this script now times `kernel` /
`ctx_kernel` alongside the original variants: one run on hardware
fills the table, and the honest comparison is ``ctx_kernel − ctx_
nobias`` vs the round-4 trace's 19.5 + 11.1 ms per step. Expectation
from the standalone evidence: the kernel needs only to stay within
~2x of the isolated mask_matvec rate (250/179 GB/s) to recover
nearly all of the ~21 ms/step the A/B attributed to the fusion.
"""

import sys
import time

sys.path.insert(0, "/root/repo")


def bench_variants(b, oy, ox, k, label):
    import jax
    import jax.numpy as jnp
    import numpy
    from jax import lax

    gen = numpy.random.Generator(numpy.random.PCG64(11))
    n = b * oy * ox
    err = jnp.asarray(gen.standard_normal((n, k), numpy.float32),
                      jnp.bfloat16)
    y = jnp.asarray(gen.standard_normal((n, k), numpy.float32),
                    jnp.bfloat16)

    def mask_matvec(e, yy):
        dz = e * (yy > 0).astype(e.dtype)
        ones = jnp.ones((1, n), e.dtype)
        return lax.dot_general(ones, dz, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)[0]

    def mask_sum(e, yy):
        dz = e * (yy > 0).astype(e.dtype)
        return dz.sum(axis=0, dtype=jnp.float32)

    def pre_masked(e, yy):
        dz = e.astype(jnp.float32) * (yy.astype(jnp.float32) > 0)
        dz = lax.optimization_barrier(dz)
        ones = jnp.ones((1, n), jnp.float32)
        return lax.dot_general(ones, dz, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)[0]

    def f32_reduce(e, yy):
        return e.astype(jnp.float32).sum(axis=0)

    # the real program's consumer structure: dz feeds a wgrad-style
    # contraction AND the bias reduce (x stands in for the im2col'd
    # input patches; a dot probes the same producer-duplication
    # fusion decision the conv triggers in the round-4 trace)
    c_in = 128
    x_in = jnp.asarray(gen.standard_normal((n, c_in), numpy.float32),
                       jnp.bfloat16)

    def ctx_full(e, yy):
        dz = e * (yy > 0).astype(e.dtype)
        gw = lax.dot_general(x_in, dz, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ones = jnp.ones((1, n), e.dtype)
        gb = lax.dot_general(ones, dz, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)[0]
        return jnp.concatenate([gw.sum(axis=0) * 1e-3, gb])

    def ctx_nobias(e, yy):
        dz = e * (yy > 0).astype(e.dtype)
        gw = lax.dot_general(x_in, dz, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        return gw.sum(axis=0) * 1e-3

    # the shipped Pallas kernel (ops/pallas_grads.py): real kernel on
    # TPU — do not run this variant through a CPU interpret session,
    # it would time the emulator
    from veles.znicz_tpu.ops import pallas_grads as PG

    def kernel(e, yy):
        return PG.bias_grad(e, yy, "strict_relu")

    def ctx_kernel(e, yy):
        dz = e * (yy > 0).astype(e.dtype)
        gw = lax.dot_general(x_in, dz, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        gb = PG.bias_grad(e, yy, "strict_relu")
        return jnp.concatenate([gw.sum(axis=0) * 1e-3, gb])

    def timed(fn, feed, reps_hi=120, reps_lo=12):
        """Unrolled data-dependent chaining: BOTH err and y perturb
        each rep (a constant y lets the mask hoist out of the loop and
        over-reads the bandwidth), rep-count difference cancels the
        tunnel round-trip. Unrolled, not lax.scan: scan-wrapping these
        dots stalled the remote-compile service indefinitely."""
        def chain(reps):
            @jax.jit
            def run(e, yy):
                acc = jnp.float32(0)
                for _ in range(reps):
                    g = fn(e, yy)
                    acc = acc + g.sum()
                    bump = g[None, :k].astype(e.dtype) * 1e-6
                    e = e + bump
                    yy = yy + bump
                return acc
            float(run(feed, y))
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                float(run(feed, y))
                best = min(best, time.perf_counter() - t0)
            return best
        return (chain(reps_hi) - chain(reps_lo)) \
            / (reps_hi - reps_lo)

    bytes_read = 2 * n * k * 2          # err + y, bf16
    print("%s  (B=%d %dx%d K=%d; %d MB read/step)"
          % (label, b, oy, ox, k, bytes_read >> 20))
    times = {}
    variants = [("mask_matvec", mask_matvec),
                ("mask_sum", mask_sum),
                ("pre_masked", pre_masked),
                ("f32_reduce", f32_reduce),
                ("ctx_full", ctx_full),
                ("ctx_nobias", ctx_nobias)]
    if PG._on_tpu():
        # interpret mode would take HOURS at these shapes and time
        # the emulator, not the kernel — the comment above made the
        # rule, this guard enforces it
        variants += [("kernel", kernel), ("ctx_kernel", ctx_kernel)]
    else:
        print("  (kernel/ctx_kernel skipped: no TPU — interpret mode "
              "would time the Pallas emulator, not the kernel)")
    for name, fn in variants:
        try:
            t = timed(fn, err)
        except Exception as exc:
            print("  %-12s FAILED: %s" % (name, str(exc)[:140]))
            continue
        times[name] = t
        print("  %-12s %7.3f ms   %7.1f GB/s effective"
              % (name, t * 1e3, bytes_read / t / 1e9), flush=True)
    if "ctx_full" in times and "ctx_nobias" in times:
        marginal = times["ctx_full"] - times["ctx_nobias"]
        print("  in-context marginal bias-reduce cost: %.3f ms "
              "(isolated form: %.3f ms)"
              % (marginal * 1e3, times.get("mask_matvec", 0) * 1e3))
    if "ctx_kernel" in times and "ctx_nobias" in times:
        print("  SHIPPED-KERNEL in-context marginal cost: %.3f ms "
              "(ops/pallas_grads.py; hold against the pathological "
              "marginal above)"
              % ((times["ctx_kernel"] - times["ctx_nobias"]) * 1e3))
    return mask_matvec, err, y


def main():
    import jax

    mask_matvec, err, y = bench_variants(128, 55, 55, 96,
                                         "conv1-shape")
    bench_variants(128, 27, 27, 256, "conv2-shape")
    hlo = jax.jit(mask_matvec).lower(err, y).compile().as_text()
    path = "/tmp/convert_reduce_repro_hlo.txt"
    with open(path, "w") as f:
        f.write(hlo)
    print("optimized HLO of the ISOLATED (fast) form ->", path)
    print("the in-program (pathological) fusions are committed at "
          "docs/convert_reduce_fusion_hlo.txt")


if __name__ == "__main__":
    main()
