"""Cluster health plane: metrics history, probes, SLO burn-rate alerts.

PRs 3 and 6 gave every process rich raw instruments (telemetry
registry, distributed traces, flight recorder, perf ledger); this
module turns them into an OPERATIONAL surface that can answer "is
this process healthy, is it meeting its objectives, and why not":

* :class:`HealthMonitor` — one per process (module-level active
  instance, :func:`get_monitor`), owning three things:

  - a **time-series ring**: a background sampler copies selected
    registry families (``veles_serving_*`` latency percentiles and
    queue depth, ``veles_cluster_*`` faults/slaves, wire bytes, step
    flops, checkpoint ages, the ``veles_slo_*`` gauges themselves)
    into bounded ``(wall, {series: value})`` snapshots at a fixed
    cadence — served as ``GET /metrics/history?window=SECS`` on
    web-status and the serving frontend;
  - **readiness checks**: named callables evaluated ON THE SAMPLER
    THREAD each tick (they may take locks, scan registries, read
    breakers); the results are cached into a probe document that
    ``GET /healthz`` / ``GET /readyz`` handlers serve with ONE
    attribute read — probe handlers never block (zlint
    ``probe-purity`` enforces this repo-wide);
  - an **SLO engine**: declarative objectives evaluated over the
    ring with the SRE-workbook multi-window burn-rate method —
    ``burn = error_ratio / (1 - target)`` over a FAST and a SLOW
    window, alert while BOTH exceed ``burn_threshold`` (the fast
    window makes alerts stop quickly once fixed, the slow window
    keeps blips from paging). Transitions land in the flight
    recorder (``telemetry.record_event`` → ``/debug/events``) and
    the ``veles_slo_*`` gauge families; firing objectives flip
    ``/readyz`` with a reason naming them.

SLO config format (``--slo-config objectives.json``, a JSON list)::

    [{"name": "serving_p99_latency",
      "kind": "threshold",                      # default
      "series": "veles_serving_latency_seconds{model=\\"mnist\\"}:p99",
      "op": "<=", "threshold": 0.25,            # good sample iff
      "target": 0.99,                           # 99% of samples good
      "fast_window": 60, "slow_window": 300,
      "burn_threshold": 1.0},
     {"name": "predict_error_ratio",
      "kind": "ratio",                          # counter-delta ratio
      "bad": "veles_serving_error_total",
      "total": "veles_serving_requests_total",
      "target": 0.999}]

Series keys are ``family`` or ``family{label="v"}`` exactly as the
ring stores them; histograms add ``:p50``/``:p99``/``:count``
suffixes. A bare family name matches the SUM over its children
(meaningful for counters/gauges).
"""

import collections
import json
import threading
import time
from contextlib import contextmanager
from urllib.parse import parse_qs, urlparse

from veles import telemetry
from veles.logger import Logger

#: registry family prefixes the ring samples by default — the
#: operational families every surface exports (adding a prefix costs
#: one dict entry per child per tick, nothing on any hot path)
DEFAULT_PREFIXES = (
    "veles_serving_", "veles_cluster_", "veles_master_",
    "veles_slave_", "veles_wire_", "veles_step_", "veles_loader_",
    "veles_checkpoint_", "veles_slo_", "veles_grad_",
    "veles_reactor_",
    # memory accounting (ISSUE 10, veles/profiling.py): host RSS/fds,
    # device allocator stats, perf-ledger + forward-cache estimates —
    # ring-sampled so /metrics/history carries memory TRAJECTORIES
    # and SLO objectives can fire on leaks
    "veles_host_", "veles_device_", "veles_perf_",
    # fleet control (ISSUE 13, veles/router.py): routed-request
    # counters/latency and backend inflight — ring-sampled so SLO
    # objectives can fire on router-observed p99 and the autoscaler's
    # own decisions are trendable in /metrics/history
    "veles_router_",
    # model health (ISSUE 15, veles/model_health.py): per-layer
    # grad/weight norms, loss z-score, non-finite step counts and the
    # verdict gauge — ring-sampled so the divergence SLOs
    # (install_model_slos) evaluate over them
    "veles_model_",
    # continual loop (ISSUE 16, veles/continual.py): the end-to-end
    # staleness gauge the burn-rate SLO evaluates over, round
    # progress, and stream-ingest prefetch/failure counters
    "veles_staleness_", "veles_continual_", "veles_stream_",
)

#: sampler cadence (seconds) and ring capacity: 1 Hz x 900 samples =
#: a 15-minute window, comfortably covering the default slow
#: burn-rate window with bounded memory
DEFAULT_INTERVAL = 1.0
DEFAULT_MAX_SAMPLES = 900

_OPS = {
    "<=": lambda v, t: v <= t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    ">": lambda v, t: v > t,
}


class SLObjective:
    """One declarative objective + its alert state (see the module
    docstring for the spec format)."""

    def __init__(self, spec):
        spec = dict(spec)
        self.name = str(spec.pop("name", "") or "")
        if not self.name:
            raise ValueError("SLO spec needs a 'name'")
        self.kind = str(spec.pop("kind", "threshold"))
        if self.kind not in ("threshold", "ratio"):
            raise ValueError("SLO %s: kind must be threshold|ratio, "
                             "not %r" % (self.name, self.kind))
        def required(key):
            value = spec.pop(key, None)
            if value is None:
                raise ValueError("SLO %s (kind %s): missing required "
                                 "key %r" % (self.name, self.kind,
                                             key))
            return value

        if self.kind == "threshold":
            self.series = str(required("series"))
            op = str(spec.pop("op", "<="))
            if op not in _OPS:
                raise ValueError("SLO %s: op must be one of %s"
                                 % (self.name, sorted(_OPS)))
            self.op_name = op
            self.op = _OPS[op]
            self.threshold = float(required("threshold"))
        else:
            self.bad = str(required("bad"))
            self.total = str(required("total"))
        self.target = float(spec.pop("target", 0.99))
        if not 0.0 < self.target < 1.0:
            raise ValueError("SLO %s: target must be in (0, 1)"
                             % self.name)
        self.fast_window = float(spec.pop("fast_window", 60.0))
        self.slow_window = float(spec.pop("slow_window", 300.0))
        self.burn_threshold = float(spec.pop("burn_threshold", 1.0))
        if spec:
            raise ValueError("SLO %s: unknown key(s) %s"
                             % (self.name, sorted(spec)))
        #: alert state (evaluated on the monitor thread only)
        self.firing = False
        self.fired_at = None
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.error_ratio = 0.0

    def describe(self):
        doc = {"kind": self.kind, "target": self.target,
               "fast_window": self.fast_window,
               "slow_window": self.slow_window,
               "burn_threshold": self.burn_threshold,
               "firing": self.firing,
               "burn_fast": round(self.burn_fast, 4),
               "burn_slow": round(self.burn_slow, 4),
               "error_ratio": round(self.error_ratio, 6)}
        if self.kind == "threshold":
            doc["series"] = self.series
            doc["op"] = self.op_name
            doc["threshold"] = self.threshold
        else:
            doc["bad"] = self.bad
            doc["total"] = self.total
        return doc


def _series_value(flat, key):
    """Resolve ``key`` against one ring sample: exact hit first, else
    the SUM over the family's labelled children (``key{...}``) — the
    natural reading for counters/gauges; percentile keys should be
    addressed exactly. None when nothing matches."""
    v = flat.get(key)
    if v is not None:
        return v
    prefix = key + "{"
    total, hit = 0.0, False
    for k, v in flat.items():
        # endswith("}") excludes the :p50/:p99/:count suffix keys
        # without also excluding label VALUES that contain a colon
        # (endpoint="host:8080")
        if k.startswith(prefix) and k.endswith("}"):
            total += v
            hit = True
    return total if hit else None


class HealthMonitor(Logger):
    """Per-process health plane: ring + readiness cache + SLO engine.

    One daemon sampler thread does ALL the work each tick (sample the
    registry, run the checks, evaluate the objectives, rebuild the
    probe cache); HTTP probe handlers only read
    :attr:`_probe_cache` — a dict replaced wholesale per tick, so the
    read is one attribute load and probes answer in microseconds even
    while a training step holds the master lock."""

    def __init__(self, interval=DEFAULT_INTERVAL,
                 max_samples=DEFAULT_MAX_SAMPLES,
                 prefixes=DEFAULT_PREFIXES):
        self.name = "health"
        self.interval = float(interval)
        self.prefixes = tuple(prefixes)
        self._lock = threading.Lock()
        #: serializes whole ticks (the sampler thread vs. the
        #: synchronous ticks add_check/add_slo trigger)
        self._tick_lock = threading.Lock()
        self._samples = collections.deque(maxlen=int(max_samples))
        self._checks = {}
        self._series_fns = {}
        self._slos = []
        self._slo_names = set()
        self._thread = None
        self._stop = threading.Event()
        self._closed = False
        self._shutting_down = False
        self._started_wall = time.time()
        # SLO gauge families (hoisted: children are resolved per
        # objective per tick, the families exactly once per registry)
        self._g_burn = telemetry.LazyChild(lambda: telemetry.gauge(
            "veles_slo_burn_rate",
            "SLO error-budget burn rate per objective and window "
            "(1.0 = burning exactly the budget)",
            ("objective", "window")))
        self._g_ratio = telemetry.LazyChild(lambda: telemetry.gauge(
            "veles_slo_error_ratio",
            "SLO error ratio over the fast window", ("objective",)))
        self._g_firing = telemetry.LazyChild(lambda: telemetry.gauge(
            "veles_slo_alert_firing",
            "1 while the objective's multi-window burn-rate alert "
            "fires", ("objective",)))
        self._probe_cache = {}
        self.tick()

    # -- lifecycle -----------------------------------------------------

    def ensure_started(self):
        """Start the sampler thread (idempotent; no-op once closed)."""
        if self._closed:
            return self
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="health-monitor")
                self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as exc:   # the plane must outlive a bad
                self.warning("health tick failed: %s: %s",
                             type(exc).__name__, exc)

    def mark_shutdown(self):
        """Flip liveness to 503 (draining/stopping process)."""
        self._shutting_down = True
        self.tick()

    def close(self):
        self._closed = True
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)

    # -- registration --------------------------------------------------

    def add_check(self, name, fn, tick=True):
        """Register readiness check ``fn() -> (ok, reason|None)`` (a
        bare bool is accepted). Evaluated on the SAMPLER thread each
        tick — it may take locks or scan state; probe handlers only
        ever read the cached verdict. ``tick=False`` defers the
        synchronous re-evaluation (batch registration: pass it for
        all but the last check)."""
        with self._lock:
            self._checks[str(name)] = fn
        if tick:
            self.tick()

    def remove_check(self, name, tick=True):
        with self._lock:
            self._checks.pop(str(name), None)
        if tick:
            self.tick()

    def add_series(self, key, fn):
        """Register a custom ring series: ``fn() -> float`` sampled
        each tick under key ``key`` (for derived quantities no gauge
        exports)."""
        with self._lock:
            self._series_fns[str(key)] = fn

    def add_slo(self, spec):
        """Register one objective (dict spec — module docstring)."""
        slo = SLObjective(spec)
        with self._lock:
            if slo.name in self._slo_names:
                raise ValueError("duplicate SLO %r" % slo.name)
            self._slo_names.add(slo.name)
            self._slos.append(slo)
        self.tick()
        return slo

    def load_slo_file(self, path):
        """Load a JSON list of objective specs; -> count added."""
        with open(path) as f:
            specs = json.load(f)
        if not isinstance(specs, list):
            raise ValueError("%s: SLO config must be a JSON list"
                             % path)
        for spec in specs:
            self.add_slo(spec)
        return len(specs)

    def slos(self):
        with self._lock:
            return list(self._slos)

    # -- the tick ------------------------------------------------------

    def tick(self, now=None):
        """One full evaluation: sample -> checks -> SLOs -> rebuild
        the probe cache. Runs on the sampler thread each interval and
        synchronously from add_check/add_slo (so registration is
        immediately visible to probes); ``now`` is injectable for
        deterministic tests."""
        with self._tick_lock:
            now = time.time() if now is None else float(now)
            flat = self._sample()
            with self._lock:
                self._samples.append((now, flat))
                samples = list(self._samples)
                checks = sorted(self._checks.items())
                slos = list(self._slos)
            checks_doc, reasons = self._run_checks(checks)
            slo_doc, slo_reasons = self._evaluate_slos(
                slos, samples, now)
            reasons.extend(slo_reasons)
            ready = not reasons and not self._shutting_down
            if self._shutting_down:
                reasons.insert(0, "shutting down")
            live_doc = {"status": "stopping" if self._shutting_down
                        else "ok",
                        "uptime_s": round(now - self._started_wall, 3)}
            ready_doc = {"ready": ready, "reasons": reasons,
                         "checks": checks_doc, "slos": slo_doc}
            with self._lock:
                self._probe_cache = {
                    "/healthz": (503 if self._shutting_down else 200,
                                 live_doc),
                    "/readyz": (200 if ready else 503, ready_doc),
                }
        return ready

    def _sample(self):
        """One flat ``{series_key: value}`` snapshot of the selected
        registry families (+ custom series fns)."""
        # memory accounting rides the monitor tick (ISSUE 10): the
        # veles_host_*/veles_device_*/veles_perf_* set_function gauges
        # are (re-)registered against the ACTIVE registry here, so
        # every monitored process exports them, registry swaps (test
        # isolation) re-acquire them, and device kinds that only exist
        # once jax finishes backend init still show up
        try:
            from veles import profiling
            profiling.register_memory_gauges()
        except Exception as exc:
            self.warning("memory gauges unavailable: %s: %s",
                         type(exc).__name__, exc)
        flat = {}
        prefixes = self.prefixes
        for fam in telemetry.get_registry().families():
            if not fam.name.startswith(prefixes):
                continue
            for items, child in fam.children():
                key = fam.name + telemetry._fmt_labels(items)
                if fam.kind == "histogram":
                    p50 = child.percentile(0.5)
                    if p50 is not None:
                        flat[key + ":p50"] = float(p50)
                        flat[key + ":p99"] = float(
                            child.percentile(0.99))
                    flat[key + ":count"] = float(child.count)
                else:
                    v = float(child.value)
                    if v == v:          # skip NaN (broken gauge fns)
                        flat[key] = v
        with self._lock:
            fns = list(self._series_fns.items())
        for key, fn in fns:
            try:
                v = float(fn())
            except Exception:
                continue
            if v == v:
                flat[key] = v
        return flat

    @staticmethod
    def _run_checks(checks):
        doc, reasons = {}, []
        for name, fn in checks:
            try:
                result = fn()
            except Exception as exc:
                result = (False, "check raised %s: %s"
                          % (type(exc).__name__, exc))
            if isinstance(result, tuple):
                ok, reason = result
            else:
                ok, reason = bool(result), None
            doc[name] = {"ok": bool(ok)}
            if reason:
                doc[name]["reason"] = str(reason)
            if not ok:
                reasons.append("%s: %s" % (name, reason or "not ready"))
        return doc, reasons

    # -- SLO evaluation ------------------------------------------------

    def _evaluate_slos(self, slos, samples, now):
        doc, reasons = {}, []
        burn_g = self._g_burn.get()
        ratio_g = self._g_ratio.get()
        firing_g = self._g_firing.get()
        for slo in slos:
            fast = self._error_ratio(slo, samples, now,
                                     slo.fast_window)
            slow = self._error_ratio(slo, samples, now,
                                     slo.slow_window)
            budget = 1.0 - slo.target
            slo.error_ratio = fast
            slo.burn_fast = fast / budget
            slo.burn_slow = slow / budget
            should_fire = slo.burn_fast >= slo.burn_threshold \
                and slo.burn_slow >= slo.burn_threshold
            if should_fire and not slo.firing:
                slo.firing = True
                slo.fired_at = now
                telemetry.record_event(
                    "slo_alert", objective=slo.name, state="firing",
                    burn_fast=round(slo.burn_fast, 3),
                    burn_slow=round(slo.burn_slow, 3),
                    error_ratio=round(fast, 6))
                self.warning(
                    "SLO %s alert FIRING (burn fast=%.2f slow=%.2f, "
                    "error ratio %.4f)", slo.name, slo.burn_fast,
                    slo.burn_slow, fast)
            elif slo.firing and not should_fire:
                slo.firing = False
                telemetry.record_event(
                    "slo_alert", objective=slo.name, state="resolved",
                    burn_fast=round(slo.burn_fast, 3),
                    burn_slow=round(slo.burn_slow, 3))
                self.info("SLO %s alert resolved", slo.name)
            burn_g.labels(slo.name, "fast").set(slo.burn_fast)
            burn_g.labels(slo.name, "slow").set(slo.burn_slow)
            ratio_g.labels(slo.name).set(fast)
            firing_g.labels(slo.name).set(1.0 if slo.firing else 0.0)
            doc[slo.name] = slo.describe()
            if slo.firing:
                reasons.append(
                    "slo:%s firing (burn fast=%.2f slow=%.2f)"
                    % (slo.name, slo.burn_fast, slo.burn_slow))
        return doc, reasons

    def _error_ratio(self, slo, samples, now, window):
        kept = [flat for wall, flat in samples
                if wall >= now - window]
        if slo.kind == "threshold":
            vals = []
            for flat in kept:
                v = _series_value(flat, slo.series)
                if v is not None:
                    vals.append(v)
            if not vals:
                return 0.0              # no data is not an outage
            bad = sum(1 for v in vals
                      if not slo.op(v, slo.threshold))
            return bad / len(vals)
        # ratio kind: counter deltas across the window
        pts = []
        for flat in kept:
            b = _series_value(flat, slo.bad)
            t = _series_value(flat, slo.total)
            if b is not None or t is not None:
                pts.append((b or 0.0, t or 0.0))
        if len(pts) < 2:
            return 0.0
        dbad = max(pts[-1][0] - pts[0][0], 0.0)
        dtot = max(pts[-1][1] - pts[0][1], 0.0)
        denom = max(dtot, dbad)
        return dbad / denom if denom > 0 else 0.0

    # -- reads ---------------------------------------------------------

    def probe(self, path):
        """Cached (code, doc) for ``/healthz`` / ``/readyz`` — ONE
        attribute read, no locks, never blocks (the zlint
        ``probe-purity`` contract for probe handlers)."""
        cache = self._probe_cache
        return cache.get(path, (404, {"error": "not found"}))

    def ready_state(self):
        """(ready, reasons) from the cached readiness verdict — the
        cheap gate hot request paths consult before doing work."""
        code, doc = self.probe("/readyz")
        return code == 200, list(doc.get("reasons", ()))

    @property
    def max_window(self):
        return self.interval * (self._samples.maxlen or 0)

    def history_doc(self, window=None):
        """The ring as ``{series: [[wall, value], ...]}`` within
        ``window`` seconds (default: everything retained) — what
        ``GET /metrics/history`` serves."""
        now = time.time()
        window = self.max_window if window is None \
            else max(float(window), 0.0)
        with self._lock:
            kept = [(w, f) for w, f in self._samples
                    if w >= now - window]
        series = {}
        for wall, flat in kept:
            t = round(wall, 3)
            for key, value in flat.items():
                series.setdefault(key, []).append([t, value])
        return {"interval_s": self.interval,
                "window_s": round(window, 3),
                "samples": len(kept), "now": round(now, 3),
                "series": series}


# -- active-monitor plumbing -------------------------------------------

_active_lock = threading.Lock()
_active = None


def get_monitor() -> HealthMonitor:
    """The process's active monitor, created (and its sampler thread
    started) on first use."""
    global _active
    with _active_lock:
        if _active is None:
            _active = HealthMonitor()
        monitor = _active
    return monitor.ensure_started()


def set_monitor(monitor):
    """Swap the active monitor (-> the previous one, NOT closed)."""
    global _active
    with _active_lock:
        previous = _active
        _active = monitor
    return previous


@contextmanager
def scoped(monitor=None):
    """``with scoped():`` — run under a fresh (or given) monitor,
    restoring and closing on exit (the per-test isolation hook)."""
    monitor = monitor if monitor is not None else HealthMonitor()
    previous = set_monitor(monitor)
    try:
        yield monitor
    finally:
        set_monitor(previous)
        monitor.close()


def health_endpoint(path):
    """Route a health HTTP path to ``(code, payload_dict)`` — always
    a reply, (404, ...) for anything that is not a health surface
    (handlers route by prefix and just serve what this returns).
    Shared by web-status and the serving frontend so both speak the
    same probe protocol:

    * ``/healthz``                    — liveness (cached, non-blocking)
    * ``/readyz``                     — readiness + reasons (cached)
    * ``/metrics/history[?window=S]`` — the time-series ring
    """
    parsed = urlparse(path)
    if parsed.path in ("/healthz", "/readyz"):
        return get_monitor().probe(parsed.path)
    if parsed.path == "/metrics/history":
        query = parse_qs(parsed.query)
        try:
            window = float(query["window"][0])
        except (KeyError, IndexError, ValueError):
            window = None
        return 200, get_monitor().history_doc(window)
    # handlers route by prefix, so a pathological "/healthzfoo" still
    # lands here — answer 404 instead of making the caller unpack None
    return 404, {"error": "not found"}
