"""Checkpoint / resume.

Re-design of ``veles/snapshotter.py`` [U] (SURVEY.md §2.7
"Snapshotter", §3.4, §5.4). The reference pickles the ENTIRE live
workflow; the TPU rebuild saves a *structured pytree checkpoint*
(weights + optimizer state + loader/decision/prng state + the effective
config) — robust across code changes and consumable by the C++ export
path — while keeping the reference's UX:

* gated by ``decision.improved`` (only better-than-best validation);
* error-stamped filenames (``<prefix>_=0.0190.ckpt.npz.gz``);
* "best" + "current" retention (older snapshots pruned);
* optional gzip/bz2/lzma compression;
* ``--snapshot file`` resume: load states into a freshly built
  workflow and continue.

Storage is PLUGGABLE (the reference's snapshotter had ODBC/S3-style
alternate backends, SURVEY.md §2.7): :class:`SnapshotStore` is a tiny
put/get/list/delete byte-blob contract, with
:class:`FileSnapshotStore` (default; local directory) and
:class:`HTTPSnapshotStore` (REST-style PUT/GET/DELETE against any
object endpoint — the S3-shaped deployment). ``--snapshot http://...``
resumes straight from the remote store.
"""

import bz2
import gzip
import io
import json
import lzma
import os
import threading
import time

import numpy

from veles import prng
from veles.config import root
from veles.units import Unit

_OPENERS = {"": open, "gz": gzip.open, "bz2": bz2.open, "xz": lzma.open}


class _BufferedStream:
    """Default ``SnapshotStore.stream``: buffer, then one ``put`` on
    clean exit (remote stores need whole blobs); ``.uri`` afterwards."""

    def __init__(self, store, name):
        self.store = store
        self.name = name
        self.uri = None

    def __enter__(self):
        self.buf = io.BytesIO()
        return self.buf

    def __exit__(self, et, ev, tb):
        if et is None:
            self.uri = self.store.put(self.name, self.buf.getvalue())
        return False


class _FileStream:
    """File-backed ``stream``: write THROUGH to disk (no second
    in-memory copy of the blob) with the write-then-rename commit."""

    def __init__(self, store, name):
        self.path = os.path.join(store.directory, name)
        self.uri = None

    def __enter__(self):
        self._f = open(self.path + ".tmp", "wb")
        return self._f

    def __exit__(self, et, ev, tb):
        self._f.close()
        if et is None:
            os.replace(self.path + ".tmp", self.path)
            self.uri = self.path
        else:
            try:
                os.remove(self.path + ".tmp")
            except OSError:
                pass
        return False


class SnapshotStore:
    """Byte-blob store contract: names are flat (the snapshotter's
    stamped filenames), payloads are opaque compressed npz bytes."""

    def put(self, name, data):
        """Store ``data`` under ``name``; -> a resolvable URI/path."""
        raise NotImplementedError

    def stream(self, name):
        """A context manager yielding a writable binary file whose
        contents commit to ``name`` on clean exit (``.uri`` holds the
        result). Default buffers and ``put``s; file-backed stores
        stream straight to disk."""
        return _BufferedStream(self, name)

    def get(self, name):
        """-> the bytes stored under ``name`` (KeyError if absent)."""
        raise NotImplementedError

    def list(self):
        """-> sorted snapshot names currently stored."""
        raise NotImplementedError

    def delete(self, name):
        """Remove ``name``; missing names are ignored (retention may
        race a manual cleanup)."""
        raise NotImplementedError


class FileSnapshotStore(SnapshotStore):
    """The default local-directory backend."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def put(self, name, data):
        with self.stream(name) as f:
            f.write(data)
        return os.path.join(self.directory, name)

    def stream(self, name):
        # write-then-rename: a kill mid-write must not leave a
        # truncated checkpoint a resume would trust
        return _FileStream(self, name)

    def get(self, name):
        path = os.path.join(self.directory, name)
        if not os.path.exists(path):
            raise KeyError(name)
        with open(path, "rb") as f:
            return f.read()

    def list(self):
        return sorted(n for n in os.listdir(self.directory)
                      if ".ckpt." in n)

    def delete(self, name):
        try:
            os.remove(os.path.join(self.directory, name))
        except OSError:
            pass


class CircuitOpenError(ConnectionError):
    """The HTTP store's circuit breaker is open: recent requests all
    failed, so callers fail FAST instead of stacking timeouts against
    a dead endpoint. Retry after the breaker's reset window."""


class HTTPSnapshotStore(SnapshotStore):
    """REST-style remote backend: ``PUT/GET/DELETE <base>/<name>``,
    ``GET <base>/`` -> JSON name list. Matches any object-store-shaped
    endpoint (an S3 bucket behind a signer, the forge host, a plain
    nginx WebDAV location); the transport is stdlib urllib, so
    zero-dependency like the rest of the service layer.

    Degradation policy (a flapping snapshot server must degrade
    checkpoint refresh, not kill it): transient transport errors and
    5xx responses retry ``retries`` times with exponential backoff;
    ``breaker_threshold`` consecutive request failures OPEN a circuit
    breaker that fails every call instantly (:class:`CircuitOpenError`)
    for ``breaker_reset`` seconds, after which ONE probe request is
    let through (half-open) — success closes the breaker, failure
    re-opens it. :meth:`metrics` exposes the counters."""

    def __init__(self, base_url, timeout=60, retries=2,
                 retry_backoff=0.1, breaker_threshold=4,
                 breaker_reset=30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = float(timeout)
        self.retries = int(retries)
        self.retry_backoff = float(retry_backoff)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset = float(breaker_reset)
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._breaker_open_until = 0.0
        self._probe_in_flight = False
        self.stats = {"requests": 0, "retries": 0, "failures": 0,
                      "breaker_trips": 0, "breaker_fast_fails": 0}

    # -- breaker bookkeeping -------------------------------------------

    def _gate(self):
        with self._lock:
            self.stats["requests"] += 1
            if not self._breaker_open_until:
                return
            now = time.monotonic()
            # half-open admits exactly ONE probe: concurrent callers
            # keep fast-failing or they would all stack their full
            # retry ladders against a possibly-still-dead endpoint
            if now < self._breaker_open_until or self._probe_in_flight:
                self.stats["breaker_fast_fails"] += 1
                raise CircuitOpenError(
                    "snapshot store %s: circuit open after %d "
                    "consecutive failures (retry in %.1fs)"
                    % (self.base_url, self._consecutive_failures,
                       max(0.0, self._breaker_open_until - now)))
            self._probe_in_flight = True

    def _record(self, ok):
        with self._lock:
            self._probe_in_flight = False
            if ok:
                self._consecutive_failures = 0
                self._breaker_open_until = 0.0
                return
            self._consecutive_failures += 1
            self.stats["failures"] += 1
            if self._consecutive_failures >= self.breaker_threshold:
                self._breaker_open_until = \
                    time.monotonic() + self.breaker_reset
                self.stats["breaker_trips"] += 1

    def breaker_open(self):
        with self._lock:
            return time.monotonic() < self._breaker_open_until

    def metrics(self):
        with self._lock:
            return dict(
                self.stats, base_url=self.base_url,
                consecutive_failures=self._consecutive_failures,
                breaker_open=time.monotonic()
                < self._breaker_open_until)

    def _request(self, method, name="", data=None):
        """One logical request -> the full response BODY bytes. The
        body read happens INSIDE the retry/breaker accounting: a
        connection that dies mid-body (truncation — the same fault
        class the chaos harness injects) must retry and count like
        any other transport failure, not escape after the breaker was
        already told the request succeeded."""
        import http.client
        import urllib.error
        import urllib.request
        self._gate()
        url = self.base_url + "/" + name
        last = None
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(url, data=data, method=method)
            if data is not None:
                req.add_header("Content-Type",
                               "application/octet-stream")
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout) as resp:
                    body = resp.read()
                self._record(ok=True)
                return body
            except urllib.error.HTTPError as exc:
                if exc.code < 500:
                    # the endpoint is alive and answered (404 etc.):
                    # not a store-health event, callers map the code
                    self._record(ok=True)
                    raise
                last = exc              # 5xx: flapping backend
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException) as exc:
                # HTTPException (e.g. BadStatusLine from a garbled
                # response) is neither URLError nor OSError; letting
                # it escape would skip _record() and leave a half-open
                # probe claimed forever
                last = exc
            if attempt < self.retries:
                with self._lock:
                    self.stats["retries"] += 1
                time.sleep(self.retry_backoff * (2 ** attempt))
        self._record(ok=False)
        raise last

    def put(self, name, data):
        self._request("PUT", name, data)
        return self.base_url + "/" + name

    def get(self, name):
        import urllib.error
        try:
            return self._request("GET", name)
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise KeyError(name) from None
            raise

    def list(self):
        """``GET <base>/`` -> JSON array. Servers may return names
        relative to the base or full object paths (an S3-style lister
        returns key prefixes) — both are accepted, normalized to
        base-relative names and filtered to ``.ckpt.`` blobs exactly
        like :meth:`FileSnapshotStore.list` (tests/test_service.py
        covers the round-trip against the reference blob server)."""
        from urllib.parse import urlsplit
        names = json.loads(self._request("GET").decode())
        prefix = urlsplit(self.base_url).path.lstrip("/")
        out = []
        for n in names:
            if "://" in n:
                # absolute-URL hrefs (some WebDAV servers return full
                # URLs, not paths): reduce to the path before the
                # base-prefix strip or every entry is dropped
                n = urlsplit(n).path
            n = n.lstrip("/")   # WebDAV-style absolute hrefs
            if prefix and n.startswith(prefix + "/"):
                n = n[len(prefix) + 1:]
            if "/" in n:
                # a full-bucket lister may return keys OUTSIDE this
                # base (another run's prefix): never surface foreign
                # checkpoints as ours
                continue
            if ".ckpt." in n:
                out.append(n)
        if names and not out:
            # an endpoint whose every name got filtered probably
            # speaks a listing dialect this normalization misses —
            # an empty list() silently disables retention/resume, so
            # say what was seen
            import logging
            logging.getLogger(type(self).__name__).warning(
                "%s/: all %d listed names filtered out (first: %r) — "
                "no checkpoints visible", self.base_url, len(names),
                names[0])
        return sorted(out)

    def delete(self, name):
        import urllib.error
        try:
            self._request("DELETE", name)
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise


#: one HTTPSnapshotStore per base URL, so repeated resolutions of the
#: same endpoint (a serving process refreshing its checkpoint every
#: few minutes) share ONE circuit breaker — without this every refresh
#: would mint a fresh store whose breaker has no memory of the
#: endpoint flapping
_STORE_CACHE = {}
_STORE_CACHE_LOCK = threading.Lock()


def store_for(target):
    """A store + name resolver for a snapshot TARGET: an http(s) URI
    maps to (a cached HTTPSnapshotStore(base), name); anything else is
    a local path handled by the file machinery."""
    if target.startswith(("http://", "https://")):
        base, _, name = target.rpartition("/")
        with _STORE_CACHE_LOCK:
            store = _STORE_CACHE.get(base)
            if store is None:
                store = _STORE_CACHE[base] = HTTPSnapshotStore(base)
        return store, name
    return None, target


class SnapshotterBase(Unit):
    """Gated checkpoint writer."""

    def __init__(self, workflow, prefix="wf", compression="gz",
                 directory=None, keep=2, export_inference=None,
                 store=None, **kwargs):
        super().__init__(workflow, **kwargs)
        if compression not in _OPENERS:
            raise ValueError("compression must be one of %s"
                             % sorted(_OPENERS))
        self.prefix = prefix
        self.compression = compression
        self.directory = directory or root.common.dirs.snapshots
        #: the storage backend; default = local FileSnapshotStore over
        #: ``directory``. Any SnapshotStore plugs in (config can name
        #: an HTTP endpoint: ``store="http://host/bucket"``).
        if isinstance(store, str):
            store = HTTPSnapshotStore(store) \
                if store.startswith(("http://", "https://")) \
                else FileSnapshotStore(store)
        self._store = store
        self.keep = keep
        self.decision = None
        self.destination = None      # last written path/URI
        self._written = []
        #: consecutive store-write failures; at ``max_store_failures``
        #: the next failure RAISES instead of warning — a permanently
        #: broken backend (dead endpoint, full disk) must not let a
        #: long run finish with stale or no checkpoints and nothing
        #: but warnings in the log (ADVICE r4)
        self._store_failures = 0
        self.max_store_failures = 3
        #: directory to (re)write the C++ inference archive into on
        #: every improved snapshot — the deployable artifact always
        #: tracks the best checkpoint (reference export-on-snapshot
        #: flow, SURVEY.md §3.5)
        self.export_inference_dir = export_inference

    @property
    def store(self):
        if self._store is None:
            self._store = FileSnapshotStore(self.directory)
        return self._store

    def initialize(self, **kwargs):
        super().initialize(**kwargs)
        self.store   # materialize (creates the directory for files)

    def suffix(self):
        metric = getattr(self.decision, "best_metric", None)
        if metric is None or not numpy.isfinite(metric):
            return "initial"
        return "=%.6g" % metric

    def run(self):
        self.export_snapshot()

    def export_snapshot(self):
        name = "%s_%s.ckpt.npz%s" % (
            self.prefix, self.suffix(),
            "." + self.compression if self.compression else "")
        payload = self.workflow.checkpoint_state()
        blob = io.BytesIO()
        numpy.savez(blob, **_flatten_tree(payload))
        # compress THROUGH the store's stream: file stores get the
        # old direct-to-disk write (no second in-memory copy of the
        # blob); buffering stores (HTTP) collect and put once
        try:
            sp = self.store.stream(name)
            with sp as sink:
                if self.compression:
                    with _OPENERS[self.compression](sink, "wb") as f:
                        f.write(blob.getvalue())
                else:
                    sink.write(blob.getvalue())
        except Exception as exc:
            # a checkpoint is auxiliary: a TRANSIENT store failure
            # (remote 503, full disk) must not kill hours of training
            # — but a store that fails every time has silently
            # disabled checkpointing, which a run owner must hear
            # about louder than log warnings
            self._store_failures += 1
            if self._store_failures >= self.max_store_failures:
                self.error(
                    "snapshot store failed %d times in a row — "
                    "checkpointing is effectively disabled",
                    self._store_failures)
                raise
            self.warning("snapshot %s NOT written (%s: %s; failure "
                         "%d/%d) — training continues", name,
                         type(exc).__name__, exc, self._store_failures,
                         self.max_store_failures)
            return None
        self._store_failures = 0
        path = sp.uri
        self.destination = path
        # same-suffix rewrites refresh their retention slot
        if name in self._written:
            self._written.remove(name)
        self._written.append(name)
        # retention: keep the last `keep` snapshots (newest == best so
        # far, since the gate only opens on improvement)
        while len(self._written) > self.keep:
            stale = self._written.pop(0)
            try:
                self.store.delete(stale)
            except Exception as exc:
                self.warning("retention delete of %s failed: %s",
                             stale, exc)
        if self.export_inference_dir:
            from veles.export_inference import export_inference
            # checkpoint_state() above already synced the at_valid view
            export_inference(self.workflow, self.export_inference_dir,
                             at_valid=True, sync=False)
            self.info("inference archive -> %s",
                      self.export_inference_dir)
        self.info("snapshot -> %s", path)
        return path


class Snapshotter(SnapshotterBase):
    pass


def load_snapshot(path):
    """Read a checkpoint written by Snapshotter back into a state
    tree. ``path``: a local file, or an ``http(s)://`` URI resolved
    through :class:`HTTPSnapshotStore` (remote resume)."""
    store, name = store_for(path)
    base = os.path.basename(name)
    comp = ""
    for suffix, opener in _OPENERS.items():
        if suffix and base.endswith("." + suffix):
            comp = suffix
    if store is not None:
        raw = store.get(name)
    else:
        with open(path, "rb") as f:
            raw = f.read()
    data = raw if not comp else \
        _OPENERS[comp](io.BytesIO(raw), "rb").read()
    npz = numpy.load(io.BytesIO(data), allow_pickle=False)
    return _unflatten_tree(dict(npz))


def _flatten_tree(tree, prefix=""):
    """Nested dicts of arrays/scalars -> flat {dotted/key: array}.
    JSON-able metadata rides along under the '__json__' key."""
    flat = {}
    meta = {}

    def rec(node, path):
        for key, value in node.items():
            sub = "%s/%s" % (path, key) if path else str(key)
            if isinstance(value, dict):
                rec(value, sub)
            elif isinstance(value, (numpy.ndarray, numpy.generic)):
                flat[sub] = numpy.asarray(value)
            elif isinstance(value, (int, float, bool, str, type(None),
                                    list, tuple)):
                meta[sub] = value
            else:  # device arrays and friends
                flat[sub] = numpy.asarray(value)

    rec(tree, prefix)
    flat["__json__"] = numpy.frombuffer(
        json.dumps(meta).encode(), dtype=numpy.uint8)
    return flat


def _unflatten_tree(flat):
    meta = {}
    if "__json__" in flat:
        meta = json.loads(bytes(flat.pop("__json__")).decode())
    tree = {}

    def insert(path, value):
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    for key, value in flat.items():
        insert(key, value)
    for key, value in meta.items():
        insert(key, value)
    return tree
