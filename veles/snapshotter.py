"""Checkpoint / resume.

Re-design of ``veles/snapshotter.py`` [U] (SURVEY.md §2.7
"Snapshotter", §3.4, §5.4). The reference pickles the ENTIRE live
workflow; the TPU rebuild saves a *structured pytree checkpoint*
(weights + optimizer state + loader/decision/prng state + the effective
config) — robust across code changes and consumable by the C++ export
path — while keeping the reference's UX:

* gated by ``decision.improved`` (only better-than-best validation);
* error-stamped filenames (``<prefix>_=0.0190.ckpt.npz.gz``);
* "best" + "current" retention (older snapshots pruned);
* optional gzip/bz2/lzma compression;
* ``--snapshot file`` resume: load states into a freshly built
  workflow and continue.

Durability layer (the reference's whole operational story rests on
"kill the run anywhere, pick it up from disk" — SURVEY.md §2.7, §5.4):

* **wall-clock gate**: ``interval=SECS`` writes rolling ``current``
  checkpoints at unit boundaries alongside the improvement-gated
  ``best`` ones, each slot with its own retention — a preempted job
  loses at most ``interval`` seconds, not every epoch since the last
  validation best;
* **manifest**: every checkpoint embeds schema version, wall time,
  config hash and a per-array sha256 — :func:`load_snapshot` verifies
  on read, so a truncated or bit-flipped blob raises
  :class:`CorruptCheckpointError` instead of resuming garbage;
* **auto-resume**: :func:`resolve_auto` scans a store (file or HTTP),
  picks the newest checkpoint whose manifest verifies and falls back
  to the next-newest on corruption, counting every rejected blob in
  ``veles_checkpoint_verify_failures_total``;
* **crash-safe commit**: the file backend fsyncs the blob AND its
  directory around the write-then-rename, so a host crash can never
  commit a zero-length "checkpoint";
* retention state is rebuilt from ``store.list()`` on initialize, so
  a resumed run keeps pruning pre-restart snapshots instead of
  growing the store without bound.

Storage is PLUGGABLE (the reference's snapshotter had ODBC/S3-style
alternate backends, SURVEY.md §2.7): :class:`SnapshotStore` is a tiny
put/get/list/delete byte-blob contract, with
:class:`FileSnapshotStore` (default; local directory) and
:class:`HTTPSnapshotStore` (REST-style PUT/GET/DELETE against any
object endpoint — the S3-shaped deployment). ``--snapshot http://...``
resumes straight from the remote store.
"""

import bz2
import gzip
import hashlib
import io
import json
import lzma
import os
import re
import threading
import time

import numpy

from veles import telemetry
from veles.config import root
from veles.units import Unit

_OPENERS = {"": open, "gz": gzip.open, "bz2": bz2.open, "xz": lzma.open}

#: bump when the checkpoint tree layout changes incompatibly
SCHEMA_VERSION = 1

#: npz entry holding the integrity manifest (JSON as uint8 bytes)
MANIFEST_KEY = "__manifest__"


class CorruptCheckpointError(Exception):
    """The checkpoint failed verification: unreadable compression/npz
    container, a manifest whose per-array digests don't match the
    payload, or a missing/extra array. A resume must treat the blob as
    absent (and fall back), never load it."""


class _BufferedStream:
    """Default ``SnapshotStore.stream``: buffer, then one ``put`` on
    clean exit (remote stores need whole blobs); ``.uri`` afterwards."""

    def __init__(self, store, name):
        self.store = store
        self.name = name
        self.uri = None

    def __enter__(self):
        self.buf = io.BytesIO()
        return self.buf

    def __exit__(self, et, ev, tb):
        if et is None:
            self.uri = self.store.put(self.name, self.buf.getvalue())
        return False


class _FileStream:
    """File-backed ``stream``: write THROUGH to disk (no second
    in-memory copy of the blob) with the write-then-rename commit."""

    def __init__(self, store, name):
        self.path = os.path.join(store.directory, name)
        self.uri = None

    def __enter__(self):
        self._f = open(self.path + ".tmp", "wb")
        return self._f

    def __exit__(self, et, ev, tb):
        committed = False
        try:
            try:
                if et is None:
                    # fsync BEFORE the rename: os.replace is atomic
                    # against concurrent readers but not against power
                    # loss — an unsynced rename can commit a zero-
                    # length "checkpoint" that a resume would trust
                    self._f.flush()
                    os.fsync(self._f.fileno())
            finally:
                self._f.close()
            if et is None:
                os.replace(self.path + ".tmp", self.path)
                self._fsync_dir()
                self.uri = self.path
                committed = True
        finally:
            if not committed:
                try:
                    os.remove(self.path + ".tmp")
                except OSError:
                    pass
        return False

    def _fsync_dir(self):
        # the rename itself lives in the directory entry; sync it too
        # (best-effort: not every filesystem supports O_RDONLY dirs)
        try:
            fd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)


class SnapshotStore:
    """Byte-blob store contract: names are flat (the snapshotter's
    stamped filenames), payloads are opaque compressed npz bytes."""

    def put(self, name, data):
        """Store ``data`` under ``name``; -> a resolvable URI/path."""
        raise NotImplementedError

    def stream(self, name):
        """A context manager yielding a writable binary file whose
        contents commit to ``name`` on clean exit (``.uri`` holds the
        result). Default buffers and ``put``s; file-backed stores
        stream straight to disk."""
        return _BufferedStream(self, name)

    def get(self, name):
        """-> the bytes stored under ``name`` (KeyError if absent)."""
        raise NotImplementedError

    def list(self):
        """-> sorted snapshot names currently stored."""
        raise NotImplementedError

    def delete(self, name):
        """Remove ``name``; missing names are ignored (retention may
        race a manual cleanup)."""
        raise NotImplementedError


class FileSnapshotStore(SnapshotStore):
    """The default local-directory backend."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def put(self, name, data):
        with self.stream(name) as f:
            f.write(data)
        return os.path.join(self.directory, name)

    def stream(self, name):
        # write-then-rename: a kill mid-write must not leave a
        # truncated checkpoint a resume would trust
        return _FileStream(self, name)

    def get(self, name):
        # open directly: an exists()-then-open pair would turn a blob
        # pruned by a concurrent writer's retention into a "store
        # down" FileNotFoundError instead of the KeyError the
        # resume/audit paths treat as raced retention
        try:
            with open(os.path.join(self.directory, name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(name)

    def list(self):
        # exclude in-progress/orphaned .tmp writes: a SIGKILL mid-
        # write leaves one behind, and surfacing it would make every
        # audit report a phantom "corrupt" checkpoint and let a
        # retention rebuild adopt (then delete) another writer's
        # in-flight blob
        return sorted(n for n in os.listdir(self.directory)
                      if ".ckpt." in n and not n.endswith(".tmp"))

    def delete(self, name):
        try:
            os.remove(os.path.join(self.directory, name))
        except OSError:
            pass


class CircuitOpenError(ConnectionError):
    """The HTTP store's circuit breaker is open: recent requests all
    failed, so callers fail FAST instead of stacking timeouts against
    a dead endpoint. Retry after the breaker's reset window."""


class HTTPSnapshotStore(SnapshotStore):
    """REST-style remote backend: ``PUT/GET/DELETE <base>/<name>``,
    ``GET <base>/`` -> JSON name list. Matches any object-store-shaped
    endpoint (an S3 bucket behind a signer, the forge host, a plain
    nginx WebDAV location); the transport is stdlib urllib, so
    zero-dependency like the rest of the service layer.

    Degradation policy (a flapping snapshot server must degrade
    checkpoint refresh, not kill it): transient transport errors and
    5xx responses retry ``retries`` times with exponential backoff;
    ``breaker_threshold`` consecutive request failures OPEN a circuit
    breaker that fails every call instantly (:class:`CircuitOpenError`)
    for ``breaker_reset`` seconds, after which ONE probe request is
    let through (half-open) — success closes the breaker, failure
    re-opens it. :meth:`metrics` exposes the counters."""

    def __init__(self, base_url, timeout=60, retries=2,
                 retry_backoff=0.1, breaker_threshold=4,
                 breaker_reset=30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = float(timeout)
        self.retries = int(retries)
        self.retry_backoff = float(retry_backoff)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset = float(breaker_reset)
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._breaker_open_until = 0.0
        self._probe_in_flight = False
        self.stats = {"requests": 0, "retries": 0, "failures": 0,
                      "breaker_trips": 0, "breaker_fast_fails": 0}

    # -- breaker bookkeeping -------------------------------------------

    def _gate(self):
        with self._lock:
            self.stats["requests"] += 1
            if not self._breaker_open_until:
                return
            now = time.monotonic()
            # half-open admits exactly ONE probe: concurrent callers
            # keep fast-failing or they would all stack their full
            # retry ladders against a possibly-still-dead endpoint
            if now < self._breaker_open_until or self._probe_in_flight:
                self.stats["breaker_fast_fails"] += 1
                raise CircuitOpenError(
                    "snapshot store %s: circuit open after %d "
                    "consecutive failures (retry in %.1fs)"
                    % (self.base_url, self._consecutive_failures,
                       max(0.0, self._breaker_open_until - now)))
            self._probe_in_flight = True

    def _record(self, ok):
        with self._lock:
            self._probe_in_flight = False
            if ok:
                self._consecutive_failures = 0
                self._breaker_open_until = 0.0
                return
            self._consecutive_failures += 1
            self.stats["failures"] += 1
            if self._consecutive_failures >= self.breaker_threshold:
                self._breaker_open_until = \
                    time.monotonic() + self.breaker_reset
                self.stats["breaker_trips"] += 1

    def breaker_open(self):
        with self._lock:
            return time.monotonic() < self._breaker_open_until

    def metrics(self):
        with self._lock:
            return dict(
                self.stats, base_url=self.base_url,
                consecutive_failures=self._consecutive_failures,
                breaker_open=time.monotonic()
                < self._breaker_open_until)

    def _request(self, method, name="", data=None):
        """One logical request -> the full response BODY bytes. The
        body read happens INSIDE the retry/breaker accounting: a
        connection that dies mid-body (truncation — the same fault
        class the chaos harness injects) must retry and count like
        any other transport failure, not escape after the breaker was
        already told the request succeeded."""
        import http.client
        import urllib.error
        import urllib.request
        self._gate()
        url = self.base_url + "/" + name
        last = None
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(url, data=data, method=method)
            if data is not None:
                req.add_header("Content-Type",
                               "application/octet-stream")
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout) as resp:
                    body = resp.read()
                self._record(ok=True)
                return body
            except urllib.error.HTTPError as exc:
                if exc.code < 500:
                    # the endpoint is alive and answered (404 etc.):
                    # not a store-health event, callers map the code
                    self._record(ok=True)
                    raise
                last = exc              # 5xx: flapping backend
            except (urllib.error.URLError, OSError,
                    http.client.HTTPException) as exc:
                # HTTPException (e.g. BadStatusLine from a garbled
                # response) is neither URLError nor OSError; letting
                # it escape would skip _record() and leave a half-open
                # probe claimed forever
                last = exc
            if attempt < self.retries:
                with self._lock:
                    self.stats["retries"] += 1
                time.sleep(self.retry_backoff * (2 ** attempt))
        self._record(ok=False)
        raise last

    def put(self, name, data):
        self._request("PUT", name, data)
        return self.base_url + "/" + name

    def get(self, name):
        import urllib.error
        try:
            return self._request("GET", name)
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise KeyError(name) from None
            raise

    def list(self):
        """``GET <base>/`` -> JSON array. Servers may return names
        relative to the base or full object paths (an S3-style lister
        returns key prefixes) — both are accepted, normalized to
        base-relative names and filtered to ``.ckpt.`` blobs exactly
        like :meth:`FileSnapshotStore.list` (tests/test_service.py
        covers the round-trip against the reference blob server)."""
        from urllib.parse import urlsplit
        names = json.loads(self._request("GET").decode())
        prefix = urlsplit(self.base_url).path.lstrip("/")
        out = []
        for n in names:
            if "://" in n:
                # absolute-URL hrefs (some WebDAV servers return full
                # URLs, not paths): reduce to the path before the
                # base-prefix strip or every entry is dropped
                n = urlsplit(n).path
            n = n.lstrip("/")   # WebDAV-style absolute hrefs
            if prefix and n.startswith(prefix + "/"):
                n = n[len(prefix) + 1:]
            if "/" in n:
                # a full-bucket lister may return keys OUTSIDE this
                # base (another run's prefix): never surface foreign
                # checkpoints as ours
                continue
            if ".ckpt." in n and not n.endswith(".tmp"):
                out.append(n)
        if names and not out:
            # an endpoint whose every name got filtered probably
            # speaks a listing dialect this normalization misses —
            # an empty list() silently disables retention/resume, so
            # say what was seen
            import logging
            logging.getLogger(type(self).__name__).warning(
                "%s/: all %d listed names filtered out (first: %r) — "
                "no checkpoints visible", self.base_url, len(names),
                names[0])
        return sorted(out)

    def delete(self, name):
        import urllib.error
        try:
            self._request("DELETE", name)
        except urllib.error.HTTPError as exc:
            if exc.code != 404:
                raise


#: one HTTPSnapshotStore per base URL, so repeated resolutions of the
#: same endpoint (a serving process refreshing its checkpoint every
#: few minutes) share ONE circuit breaker — without this every refresh
#: would mint a fresh store whose breaker has no memory of the
#: endpoint flapping
_STORE_CACHE = {}
_STORE_CACHE_LOCK = threading.Lock()


def _cached_http_store(base):
    """ONE HTTPSnapshotStore per base URL, so every reader/writer of
    an endpoint shares its circuit-breaker state."""
    with _STORE_CACHE_LOCK:
        store = _STORE_CACHE.get(base)
        if store is None:
            store = _STORE_CACHE[base] = HTTPSnapshotStore(base)
    return store


def store_for(target):
    """A store + name resolver for a snapshot TARGET: an http(s) URI
    maps to (a cached HTTPSnapshotStore(base), name); anything else is
    a local path handled by the file machinery."""
    if target.startswith(("http://", "https://")):
        base, _, name = target.rpartition("/")
        return _cached_http_store(base), name
    return None, target


def store_for_base(target, create=True):
    """A :class:`SnapshotStore` over a checkpoint LOCATION (not one
    blob): an ``http(s)://`` base URL (breaker-shared via the same
    cache as :func:`store_for`) or a local directory.

    ``create=False`` is the READ-side contract (auto-resume, store
    audit): a missing local directory raises FileNotFoundError instead
    of being silently created — a typo'd ``--snapshot auto:PATH`` must
    fail loudly, never read as "empty store, start fresh"."""
    if isinstance(target, SnapshotStore):
        return target
    if target.startswith(("http://", "https://")):
        return _cached_http_store(target.rstrip("/"))
    if not create and not os.path.isdir(target):
        raise FileNotFoundError(
            "snapshot store directory %r does not exist — resuming or "
            "auditing a store never creates it (check the path, or "
            "mkdir it first)" % (target,))
    return FileSnapshotStore(target)


# -- integrity manifest ------------------------------------------------


def config_fingerprint():
    """sha256 over the effective ``root`` config (stable key order) —
    stamped into every manifest so an operator can tell which config a
    checkpoint was trained under; a mismatch on resume is WARNED, not
    fatal (configs legitimately evolve between restarts)."""
    try:
        blob = json.dumps(root.to_dict(), sort_keys=True, default=str)
    except Exception:
        return None
    return hashlib.sha256(blob.encode()).hexdigest()


def _array_digest(arr):
    arr = numpy.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def dump_checkpoint(tree, slot="best", extra_meta=None):
    """State tree -> UNCOMPRESSED npz bytes with an embedded manifest
    (schema version, wall time, config hash, per-array sha256)."""
    flat = _flatten_tree(tree)
    manifest = {
        "schema": SCHEMA_VERSION,
        "wall_time": time.time(),
        "slot": slot,
        "config_hash": config_fingerprint(),
        "arrays": {k: _array_digest(v) for k, v in flat.items()},
    }
    if extra_meta:
        manifest.update(extra_meta)
    flat[MANIFEST_KEY] = numpy.frombuffer(
        json.dumps(manifest).encode(), dtype=numpy.uint8)
    blob = io.BytesIO()
    numpy.savez(blob, **flat)
    return blob.getvalue()


def _verify_flat(flat, manifest, name):
    digests = manifest.get("arrays")
    if not isinstance(digests, dict):
        raise CorruptCheckpointError(
            "%s: manifest carries no array digests" % name)
    if set(digests) != set(flat):
        raise CorruptCheckpointError(
            "%s: manifest names %d arrays, payload has %d (missing: %s"
            " / extra: %s)" % (name, len(digests), len(flat),
                               sorted(set(digests) - set(flat))[:3],
                               sorted(set(flat) - set(digests))[:3]))
    for key, digest in digests.items():
        if _array_digest(flat[key]) != digest:
            raise CorruptCheckpointError(
                "%s: array %r fails its sha256 — bit rot or a torn "
                "write" % (name, key))


def parse_checkpoint(raw, name=""):
    """Compressed checkpoint bytes -> ``(flat_arrays, manifest)``,
    VERIFIED when a manifest is present (``manifest`` is None for
    legacy pre-manifest blobs, which cannot be verified). Raises
    :class:`CorruptCheckpointError` on any unreadable or
    digest-mismatched payload."""
    comp = _compression_of(name)
    try:
        data = raw if not comp else \
            _OPENERS[comp](io.BytesIO(raw), "rb").read()
        npz = numpy.load(io.BytesIO(data), allow_pickle=False)
        flat = dict(npz)
    except Exception as exc:
        # truncated gzip (EOFError), a torn npz (zipfile errors),
        # anything else mid-container: one fault class for resumes
        raise CorruptCheckpointError(
            "%s: unreadable checkpoint (%s: %s)"
            % (name or "<bytes>", type(exc).__name__, exc)) from exc
    manifest = None
    if MANIFEST_KEY in flat:
        try:
            manifest = json.loads(bytes(flat.pop(MANIFEST_KEY)).decode())
        except Exception as exc:
            raise CorruptCheckpointError(
                "%s: undecodable manifest (%s)" % (name, exc)) from exc
        _verify_flat(flat, manifest, name or "<bytes>")
    return flat, manifest


def _compression_of(name):
    base = os.path.basename(name)
    for suffix in _OPENERS:
        if suffix and base.endswith("." + suffix):
            return suffix
    return ""


# -- checkpoint telemetry ----------------------------------------------

_WRITE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
                  60.0)
_last_success = {"t": None}


def _age_of_last_success():
    t = _last_success["t"]
    return -1.0 if t is None else max(0.0, time.time() - t)


def _record_write(slot, nbytes, seconds):
    telemetry.counter(
        "veles_checkpoint_writes_total",
        "Checkpoints committed to the store, by retention slot",
        ("slot",)).labels(slot).inc()
    telemetry.counter(
        "veles_checkpoint_bytes_total",
        "Bytes committed to the snapshot store").inc(nbytes)
    telemetry.histogram(
        "veles_checkpoint_write_seconds",
        "Wall time of one checkpoint serialize+commit", ("slot",),
        buckets=_WRITE_BUCKETS).labels(slot).observe(seconds)
    _last_success["t"] = time.time()
    telemetry.gauge(
        "veles_checkpoint_last_success_age_seconds",
        "Seconds since a checkpoint last committed (-1: never)"
    ).set_function(_age_of_last_success)


def _count_verify_failure():
    telemetry.counter(
        "veles_checkpoint_verify_failures_total",
        "Corrupt checkpoints observed (once per blob per store "
        "scan)").inc()


def _count_diverged_skip():
    telemetry.counter(
        "veles_checkpoint_diverged_skips_total",
        "Checkpoints skipped by auto-resume/refresh because their "
        "MANIFEST carries model-health verdict 'diverged'").inc()


def health_stamp_meta():
    """The ``extra_meta`` every checkpoint writer stamps: the model
    monitor's current verdict + stats snapshot under ``model_health``
    — what lets ``resolve_auto`` and the serving registry's refresh
    skip blobs written while the model was diverging — plus, when a
    continual run registered an ingest clock, ``ingest_wall``: the
    wall time of the newest sample behind these weights, the number
    the end-to-end staleness SLO (veles/continual.py) measures a
    serving replica against."""
    from veles import continual, model_health
    meta = {"model_health":
            model_health.get_model_monitor().manifest_stamp()}
    wall = continual.ingest_wall()
    if wall:
        meta["ingest_wall"] = float(wall)
    return meta


class _CountingSink:
    """Write-through wrapper counting the bytes actually handed to
    the store — i.e. COMPRESSED size, which is what the bytes-written
    telemetry and capacity dashboards care about."""

    def __init__(self, sink):
        self._sink = sink
        self.nbytes = 0

    def write(self, data):
        self.nbytes += len(data)
        return self._sink.write(data)

    def flush(self):
        flush = getattr(self._sink, "flush", None)
        if flush is not None:
            flush()


def write_checkpoint(store, name, tree, compression="gz", slot="best",
                     extra_meta=None):
    """Serialize ``tree`` (manifest embedded) and commit it to
    ``store`` under ``name`` -> ``(uri, nbytes)``. Telemetry is
    recorded here so every writer (Snapshotter unit, master persist)
    shares the same ``veles_checkpoint_*`` series."""
    t0 = time.perf_counter()
    data = dump_checkpoint(tree, slot=slot, extra_meta=extra_meta)
    sp = store.stream(name)
    with sp as sink:
        counting = _CountingSink(sink)
        if compression:
            with _OPENERS[compression](counting, "wb") as f:
                f.write(data)
        else:
            counting.write(data)
    _record_write(slot, counting.nbytes, time.perf_counter() - t0)
    # flight-recorder log entry: postmortems need to know WHICH
    # checkpoint existed when the cluster degraded
    telemetry.record_event("checkpoint_written", name=name, slot=slot,
                           bytes=counting.nbytes)
    return sp.uri, counting.nbytes


#: any rolling-slot name (the snapshotter's ``current`` slot, the
#: master's ``master`` slot): the improvement-gated "best" retention
#: must never adopt these as metric-stamped snapshots
_ROLLING_RE = re.compile(r"_(current|master)-\d+\.ckpt\.")

#: what may follow ``<prefix>_`` in one of OUR checkpoint names: the
#: improvement stamp, the pre-metric "initial" dump, or a rolling
#: slot. Anything else under the prefix belongs to a sibling workflow
#: whose name merely extends ours ("mnist" vs "mnist_big"): a bare
#: startswith would adopt — and resume — its checkpoints
_OWN_STAMP_RE = re.compile(
    r"(?:=[^/]*?|initial|(?:current|master)-\d+)\.ckpt\.")


def _under_prefix(name, prefixes):
    return any(p and name.startswith(p + "_")
               and _OWN_STAMP_RE.match(name[len(p) + 1:])
               for p in prefixes)


class RollingSlot:
    """Rolling retention slot over sequence-named checkpoints
    (``<prefix>_<marker>-NNNNNNNN.ckpt.npz[.gz]``): keeps the last
    ``keep``, prunes the rest, and — crucially for restarts — can
    rebuild its state from ``store.list()`` so a resumed process keeps
    pruning the snapshots its predecessor wrote."""

    def __init__(self, store, prefix, marker="current", keep=2):
        self.store = store
        self.prefix = prefix
        self.marker = marker
        self.keep = int(keep)
        self._names = []
        self._seq = 0
        self._pattern = re.compile(
            re.escape(prefix) + "_" + re.escape(marker)
            + r"-(\d+)\.ckpt\.")

    def rebuild(self, logger=None, names=None):
        """Re-adopt this slot's names from the store (oldest first by
        sequence number); -> how many were found. ``names`` lets a
        caller that already listed the store share one listing."""
        if names is None:
            try:
                names = self.store.list()
            except Exception as exc:
                # degrade, but never silently: with the rebuild
                # skipped the sequence restarts at 0 (new writes
                # shadow the predecessor's low numbers) and its
                # high-sequence blobs escape retention until a later
                # successful rebuild
                if logger is not None:
                    logger.warning(
                        "%s-slot retention rebuild skipped: store "
                        "list failed (%s)", self.marker, exc)
                return 0
        found = sorted((int(m.group(1)), n) for n in names
                       for m in (self._pattern.match(n),) if m)
        self._names = [n for _, n in found]
        self._seq = found[-1][0] if found else 0
        return len(found)

    def next_name(self, compression="gz"):
        self._seq += 1
        return "%s_%s-%08d.ckpt.npz%s" % (
            self.prefix, self.marker, self._seq,
            "." + compression if compression else "")

    def commit(self, name, logger=None):
        """Record a committed write and prune past ``keep``; -> the
        pruned names (delete failures are non-fatal: retention may
        race a manual cleanup — but they are WARNED, since a store
        whose deletes always fail grows one blob per write forever)."""
        if name in self._names:
            self._names.remove(name)
        self._names.append(name)
        pruned = []
        while len(self._names) > self.keep:
            stale = self._names.pop(0)
            try:
                self.store.delete(stale)
            except Exception as exc:
                if logger is not None:
                    logger.warning("retention delete of %s failed: %s",
                                   stale, exc)
            pruned.append(stale)
        return pruned


# -- store audit / auto-resume -----------------------------------------


class CheckpointInfo:
    """One store entry as seen by :func:`scan_checkpoints`."""

    __slots__ = ("name", "status", "manifest", "error")

    def __init__(self, name, status, manifest=None, error=None):
        self.name = name
        self.status = status          # "valid" | "corrupt" | "legacy"
        self.manifest = manifest
        self.error = error

    @property
    def wall_time(self):
        if self.manifest:
            try:
                return float(self.manifest.get("wall_time"))
            except (TypeError, ValueError):
                pass
        return None

    @property
    def health_verdict(self):
        """The model-health verdict stamped at write time
        (healthy/suspect/diverged), or None for pre-ISSUE-15 and
        legacy blobs."""
        if self.manifest:
            doc = self.manifest.get("model_health")
            if isinstance(doc, dict):
                return doc.get("verdict")
        return None

    @property
    def ingest_wall(self):
        """Wall time of the newest sample behind these weights
        (continual runs, ISSUE 16), or None for non-streaming blobs."""
        if self.manifest:
            try:
                return float(self.manifest.get("ingest_wall"))
            except (TypeError, ValueError):
                pass
        return None

    def __repr__(self):
        return "CheckpointInfo(%r, %s)" % (self.name, self.status)


def scan_checkpoints(target):
    """Audit every checkpoint in a store (directory, http(s) base URL
    or a :class:`SnapshotStore`): -> ``[CheckpointInfo]`` with
    manifest-verified ``valid`` entries first (newest wall time
    leading), then ``legacy`` (pre-manifest, unverifiable), then
    ``corrupt``. The ``checkpoints`` CLI subcommand and
    :func:`resolve_auto` are both views over this. Transport failures
    PROPAGATE (matching resolve_auto's loud-failure contract): a
    flaky store must never read as "holds corrupt checkpoints" —
    the audit gate reserves that verdict for real corruption."""
    store = store_for_base(target, create=False)
    infos = []
    for name in store.list():
        try:
            raw = store.get(name)
        except KeyError:
            continue                  # raced retention
        try:
            _, manifest = parse_checkpoint(raw, name)
        except CorruptCheckpointError as exc:
            infos.append(CheckpointInfo(name, "corrupt",
                                        error=str(exc)))
            continue
        infos.append(CheckpointInfo(
            name, "valid" if manifest else "legacy", manifest=manifest))
    rank = {"valid": 0, "legacy": 1, "corrupt": 2}
    # name DESC first, then a stable sort by (status, wall time): two
    # writes inside one clock tick tie on wall_time, and rolling-slot
    # names are zero-padded so the higher sequence is the newer one
    infos.sort(key=lambda i: i.name, reverse=True)
    infos.sort(key=lambda i: (rank[i.status], -(i.wall_time or 0.0)))
    return infos


def resolve_auto(target, logger=None, prefixes=None):
    """``--snapshot auto``: pick the newest checkpoint in ``target``
    whose manifest VERIFIES, falling back past corruption (every
    corrupt blob observed counts once per scan in
    ``veles_checkpoint_verify_failures_total`` — a corrupt blob's own
    wall time is unreadable, so "newer than the winner" cannot be
    decided and the count is per observation, not per fallback).
    Legacy pre-manifest blobs are never auto-resumed (resume them by
    explicit path). Each blob is fetched and hashed exactly ONCE — on
    a remote store the resume-latency window is slaves burning their
    reconnect budget.

    ``prefixes``: when given, only names that are one of these
    prefixes followed by our own stamp shapes
    (``<prefix>_=<metric>/_initial/_current-N/_master-N``) are
    candidates — on a SHARED snapshot directory, workflow A resuming
    "newest in the store" must never adopt workflow B's newer
    checkpoint (wrong weights grafted onto coincident unit names, or
    a set_state shape crash), including a B named ``A_b`` that a bare
    prefix match would let through.

    -> ``(state_tree, name, n_corrupt)`` or ``None`` when the store
    holds no verifiable checkpoint. Transport errors propagate: a
    DOWN store must fail the resume loudly, never read as "empty
    store, start fresh"."""
    store = store_for_base(target, create=False)
    best = None                     # (wall_time, name, flat, manifest)
    corrupt = 0
    for name in store.list():
        if prefixes and not _under_prefix(name, prefixes):
            continue                # another workflow's checkpoint
        try:
            raw = store.get(name)
        except KeyError:
            continue                # raced retention
        try:
            flat, manifest = parse_checkpoint(raw, name)
        except CorruptCheckpointError as exc:
            corrupt += 1
            _count_verify_failure()
            if logger is not None:
                logger.warning("checkpoint %s rejected: %s",
                               name, exc)
            continue
        if manifest is None:
            continue                # legacy: explicit-path only
        health_doc = manifest.get("model_health")
        if isinstance(health_doc, dict) \
                and health_doc.get("verdict") == "diverged":
            # stamped while the model-health plane judged the run
            # diverged: never auto-resume it — the whole point of the
            # verdict is that a serving fleet / restart must not pick
            # up a blown-up model
            _count_diverged_skip()
            if logger is not None:
                logger.warning(
                    "checkpoint %s skipped: model-health verdict "
                    "'diverged' (%s)", name,
                    "; ".join(health_doc.get("reasons") or ()) or "?")
            continue
        try:
            wall = float(manifest.get("wall_time") or 0.0)
        except (TypeError, ValueError):
            wall = 0.0
        if best is None or (wall, name) > (best[0], best[1]):
            best = (wall, name, flat, manifest)
    if best is None:
        return None
    _, name, flat, manifest = best
    here = config_fingerprint()
    stamped = manifest.get("config_hash")
    if logger is not None and here and stamped and here != stamped:
        logger.warning(
            "checkpoint %s was written under a different config "
            "(hash %s… vs current %s…) — resuming anyway",
            name, stamped[:10], here[:10])
    return _unflatten_tree(flat), name, corrupt


class SnapshotterBase(Unit):  # zlint: disable=checkpoint-state (sequence/retention are rebuilt from store.list() in initialize; the wall-clock gate and failure budget are deliberately per-process)
    """Gated checkpoint writer."""

    def __init__(self, workflow, prefix="wf", compression="gz",
                 directory=None, keep=2, export_inference=None,
                 store=None, interval=None, keep_interval=2, **kwargs):
        super().__init__(workflow, **kwargs)
        if compression not in _OPENERS:
            raise ValueError("compression must be one of %s"
                             % sorted(_OPENERS))
        self.prefix = prefix
        self.compression = compression
        self.directory = directory or root.common.dirs.snapshots
        #: wall-clock gate (seconds): when set, rolling ``current``
        #: checkpoints are written at the first unit boundary after
        #: ``interval`` elapsed since the last write — preemption
        #: bounds the loss to this window even when validation never
        #: improves. None keeps the improvement-only reference gate.
        self.interval = None if not interval else float(interval)
        self.keep_interval = int(keep_interval)
        self._current_slot = None     # RollingSlot, built with store
        self._last_write = time.monotonic()
        #: the storage backend; default = local FileSnapshotStore over
        #: ``directory``. Any SnapshotStore plugs in (config can name
        #: an HTTP endpoint: ``store="http://host/bucket"``).
        if isinstance(store, str):
            store = HTTPSnapshotStore(store) \
                if store.startswith(("http://", "https://")) \
                else FileSnapshotStore(store)
        self._store = store
        self.keep = keep
        self.decision = None
        self.destination = None      # last written path/URI
        self._written = []
        #: consecutive store-write failures; at ``max_store_failures``
        #: the next failure RAISES instead of warning — a permanently
        #: broken backend (dead endpoint, full disk) must not let a
        #: long run finish with stale or no checkpoints and nothing
        #: but warnings in the log (ADVICE r4)
        self._store_failures = 0
        self.max_store_failures = 3
        #: directory to (re)write the C++ inference archive into on
        #: every improved snapshot — the deployable artifact always
        #: tracks the best checkpoint (reference export-on-snapshot
        #: flow, SURVEY.md §3.5)
        self.export_inference_dir = export_inference

    @property
    def store(self):
        if self._store is None:
            self._store = FileSnapshotStore(self.directory)
        return self._store

    def initialize(self, **kwargs):
        super().initialize(**kwargs)
        self.store   # materialize (creates the directory for files)
        self._current_slot = RollingSlot(
            self.store, self.prefix, keep=self.keep_interval)
        self._rebuild_retention()

    def _rebuild_retention(self):
        """Re-adopt this prefix's snapshots from the store: after a
        resume, ``_written`` used to start empty, so retention forgot
        every pre-restart snapshot and the store grew without bound."""
        try:
            names = self.store.list()
        except Exception as exc:
            self.warning("retention rebuild skipped: store list "
                         "failed (%s)", exc)
            return
        # ONE listing shared by both slots: a second round-trip on an
        # HTTP store would also let a concurrent writer slip between
        # the current-slot and best-slot views
        self._current_slot.rebuild(logger=self, names=names)
        best = []
        for name in names:
            if not name.startswith(self.prefix + "_") \
                    or _ROLLING_RE.search(name):
                continue
            rest = name[len(self.prefix) + 1:]
            # ONLY this snapshotter's own stamped shapes: a sibling
            # workflow named "<prefix>_extra" sharing the store must
            # never have its snapshots adopted (and pruned!) here
            if rest.startswith("initial.ckpt."):
                metric = numpy.inf      # "initial" prunes first
            elif rest.startswith("="):
                try:
                    metric = float(rest[1:rest.index(".ckpt.")])
                except ValueError:
                    continue
            else:
                continue
            best.append((metric, name))
        # prune order is pop(0): worst metric (largest error) first,
        # matching the improvement gate's "newest == best" invariant
        best.sort(key=lambda t: (-t[0], t[1]))
        self._written = [n for _, n in best]
        self._prune(self._written, self.keep)

    def _prune(self, written, keep):
        while len(written) > keep:
            stale = written.pop(0)
            try:
                self.store.delete(stale)
            except Exception as exc:
                self.warning("retention delete of %s failed: %s",
                             stale, exc)

    def suffix(self):
        metric = getattr(self.decision, "best_metric", None)
        if metric is None or not numpy.isfinite(metric):
            return "initial"
        return "=%.6g" % metric

    def run(self):
        if self.interval is None:
            # reference mode: the GRAPH gate (gate_skip = ~improved)
            # decides; a direct run() call means "export now" — both
            # the scheduler contract and tests rely on that
            self.export_snapshot()
            return
        # interval mode: the graph gate stays open and run() fires at
        # every unit boundary, so the gating moves in here
        improved = self.decision is not None \
            and bool(getattr(self.decision, "improved", False))
        if improved:
            self.export_snapshot()
        elif time.monotonic() - self._last_write >= self.interval:
            # re-arm BEFORE the attempt: a failed write must wait a
            # full interval to retry, not re-fire at the very next
            # unit boundary — back-to-back retries would burn the
            # 3-strike transient-failure budget inside one brief
            # store outage and kill the run
            self._last_write = time.monotonic()
            self.export_snapshot(slot="current")

    def export_snapshot(self, slot="best"):
        """Write one checkpoint into ``slot`` ("best": improvement-
        gated, metric-stamped name; "current": rolling wall-clock /
        shutdown slot with its own retention)."""
        if slot == "best":
            name = "%s_%s.ckpt.npz%s" % (
                self.prefix, self.suffix(),
                "." + self.compression if self.compression else "")
        else:
            if self._current_slot is None:
                self._current_slot = RollingSlot(
                    self.store, self.prefix, keep=self.keep_interval)
                self._current_slot.rebuild(logger=self)
            name = self._current_slot.next_name(self.compression)
        try:
            # the state build is INSIDE the guard too (mirroring the
            # master's persist_state): a transient get_state failure
            # must degrade this checkpoint, not kill the run
            payload = self.workflow.checkpoint_state()
            # the MANIFEST carries the model-health verdict the run
            # held at write time: resolve_auto and the serving
            # registry's refresh skip 'diverged' blobs
            path, _ = write_checkpoint(
                self.store, name, payload,
                compression=self.compression, slot=slot,
                extra_meta=health_stamp_meta())
        except Exception as exc:
            # a checkpoint is auxiliary: a TRANSIENT store failure
            # (remote 503, full disk) must not kill hours of training
            # — but a store that fails every time has silently
            # disabled checkpointing, which a run owner must hear
            # about louder than log warnings
            self._store_failures += 1
            if self._store_failures >= self.max_store_failures:
                self.error(
                    "snapshot store failed %d times in a row — "
                    "checkpointing is effectively disabled",
                    self._store_failures)
                raise
            self.warning("snapshot %s NOT written (%s: %s; failure "
                         "%d/%d) — training continues", name,
                         type(exc).__name__, exc, self._store_failures,
                         self.max_store_failures)
            return None
        self._store_failures = 0
        self.destination = path
        self._last_write = time.monotonic()
        if slot == "best":
            # same-suffix rewrites refresh their retention slot
            if name in self._written:
                self._written.remove(name)
            self._written.append(name)
            # retention: keep the last `keep` snapshots (newest ==
            # best so far, since the gate only opens on improvement)
            self._prune(self._written, self.keep)
        else:
            self._current_slot.commit(name, logger=self)
        if slot == "best" and self.export_inference_dir:
            from veles.export_inference import export_inference
            # checkpoint_state() above already synced the at_valid view
            export_inference(self.workflow, self.export_inference_dir,
                             at_valid=True, sync=False)
            self.info("inference archive -> %s",
                      self.export_inference_dir)
        self.info("snapshot [%s] -> %s", slot, path)
        return path

    def preempt_snapshot(self):
        """The SIGTERM path (Launcher): one final forced ``current``-
        slot checkpoint regardless of gates, so a preempted job
        resumes from its very last unit boundary."""
        try:
            return self.export_snapshot(slot="current")
        except Exception as exc:
            # the process is exiting: a dead store must not turn a
            # clean preemption into a crash loop
            self.warning("preemption checkpoint failed: %s", exc)
            return None


class Snapshotter(SnapshotterBase):
    pass


def load_snapshot(path):
    """Read a checkpoint written by Snapshotter back into a state
    tree, VERIFYING its embedded manifest when present (legacy
    pre-manifest blobs load unverified). ``path``: a local file, or an
    ``http(s)://`` URI resolved through :class:`HTTPSnapshotStore`
    (remote resume). Raises :class:`CorruptCheckpointError` on a
    truncated, bit-flipped or otherwise unreadable blob."""
    return load_snapshot_meta(path)[0]


def load_snapshot_meta(path):
    """:func:`load_snapshot` that also returns the verified manifest
    (None for legacy blobs) — readers that gate on manifest fields
    (the serving registry's refresh checks the model-health verdict)
    use this instead of re-fetching the blob."""
    store, name = store_for(path)
    if store is not None:
        raw = store.get(name)
    else:
        with open(path, "rb") as f:
            raw = f.read()
    flat, manifest = parse_checkpoint(raw, name)
    return _unflatten_tree(flat), manifest


def _flatten_tree(tree, prefix=""):
    """Nested dicts of arrays/scalars -> flat {dotted/key: array}.
    JSON-able metadata rides along under the '__json__' key."""
    flat = {}
    meta = {}

    def rec(node, path):
        for key, value in node.items():
            sub = "%s/%s" % (path, key) if path else str(key)
            if isinstance(value, dict):
                rec(value, sub)
            elif isinstance(value, (numpy.ndarray, numpy.generic)):
                flat[sub] = numpy.asarray(value)
            elif isinstance(value, (int, float, bool, str, type(None),
                                    list, tuple)):
                meta[sub] = value
            else:  # device arrays and friends
                flat[sub] = numpy.asarray(value)

    rec(tree, prefix)
    flat["__json__"] = numpy.frombuffer(
        json.dumps(meta).encode(), dtype=numpy.uint8)
    return flat


def _unflatten_tree(flat):
    meta = {}
    if "__json__" in flat:
        meta = json.loads(bytes(flat.pop("__json__")).decode())
    tree = {}

    def insert(path, value):
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    for key, value in flat.items():
        insert(key, value)
    for key, value in meta.items():
        insert(key, value)
    return tree
