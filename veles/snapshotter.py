"""Checkpoint / resume.

Re-design of ``veles/snapshotter.py`` [U] (SURVEY.md §2.7
"Snapshotter", §3.4, §5.4). The reference pickles the ENTIRE live
workflow; the TPU rebuild saves a *structured pytree checkpoint*
(weights + optimizer state + loader/decision/prng state + the effective
config) — robust across code changes and consumable by the C++ export
path — while keeping the reference's UX:

* gated by ``decision.improved`` (only better-than-best validation);
* error-stamped filenames (``<prefix>_=0.0190.ckpt.npz.gz``);
* "best" + "current" retention (older snapshots pruned);
* optional gzip/bz2/lzma compression;
* ``--snapshot file`` resume: load states into a freshly built
  workflow and continue.
"""

import bz2
import gzip
import io
import json
import lzma
import os

import numpy

from veles import prng
from veles.config import root
from veles.units import Unit

_OPENERS = {"": open, "gz": gzip.open, "bz2": bz2.open, "xz": lzma.open}


class SnapshotterBase(Unit):
    """Gated checkpoint writer."""

    def __init__(self, workflow, prefix="wf", compression="gz",
                 directory=None, keep=2, export_inference=None,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        if compression not in _OPENERS:
            raise ValueError("compression must be one of %s"
                             % sorted(_OPENERS))
        self.prefix = prefix
        self.compression = compression
        self.directory = directory or root.common.dirs.snapshots
        self.keep = keep
        self.decision = None
        self.destination = None      # last written path
        self._written = []
        #: directory to (re)write the C++ inference archive into on
        #: every improved snapshot — the deployable artifact always
        #: tracks the best checkpoint (reference export-on-snapshot
        #: flow, SURVEY.md §3.5)
        self.export_inference_dir = export_inference

    def initialize(self, **kwargs):
        super().initialize(**kwargs)
        os.makedirs(self.directory, exist_ok=True)

    def suffix(self):
        metric = getattr(self.decision, "best_metric", None)
        if metric is None or not numpy.isfinite(metric):
            return "initial"
        return "=%.6g" % metric

    def run(self):
        self.export_snapshot()

    def export_snapshot(self):
        path = os.path.join(
            self.directory, "%s_%s.ckpt.npz%s" % (
                self.prefix, self.suffix(),
                "." + self.compression if self.compression else ""))
        payload = self.workflow.checkpoint_state()
        blob = io.BytesIO()
        numpy.savez(blob, **_flatten_tree(payload))
        opener = _OPENERS[self.compression]
        with opener(path, "wb") as f:
            f.write(blob.getvalue())
        self.destination = path
        # same-suffix rewrites refresh their retention slot
        if path in self._written:
            self._written.remove(path)
        self._written.append(path)
        # retention: keep the last `keep` snapshots (newest == best so
        # far, since the gate only opens on improvement)
        while len(self._written) > self.keep:
            stale = self._written.pop(0)
            try:
                os.remove(stale)
            except OSError:
                pass
        if self.export_inference_dir:
            from veles.export_inference import export_inference
            # checkpoint_state() above already synced the at_valid view
            export_inference(self.workflow, self.export_inference_dir,
                             at_valid=True, sync=False)
            self.info("inference archive -> %s",
                      self.export_inference_dir)
        self.info("snapshot -> %s", path)
        return path


class Snapshotter(SnapshotterBase):
    pass


def load_snapshot(path):
    """Read a checkpoint written by Snapshotter back into a state tree."""
    base = os.path.basename(path)
    comp = ""
    for suffix, opener in _OPENERS.items():
        if suffix and base.endswith("." + suffix):
            comp = suffix
    with _OPENERS[comp](path, "rb") as f:
        data = f.read()
    npz = numpy.load(io.BytesIO(data), allow_pickle=False)
    return _unflatten_tree(dict(npz))


def _flatten_tree(tree, prefix=""):
    """Nested dicts of arrays/scalars -> flat {dotted/key: array}.
    JSON-able metadata rides along under the '__json__' key."""
    flat = {}
    meta = {}

    def rec(node, path):
        for key, value in node.items():
            sub = "%s/%s" % (path, key) if path else str(key)
            if isinstance(value, dict):
                rec(value, sub)
            elif isinstance(value, (numpy.ndarray, numpy.generic)):
                flat[sub] = numpy.asarray(value)
            elif isinstance(value, (int, float, bool, str, type(None),
                                    list, tuple)):
                meta[sub] = value
            else:  # device arrays and friends
                flat[sub] = numpy.asarray(value)

    rec(tree, prefix)
    flat["__json__"] = numpy.frombuffer(
        json.dumps(meta).encode(), dtype=numpy.uint8)
    return flat


def _unflatten_tree(flat):
    meta = {}
    if "__json__" in flat:
        meta = json.loads(bytes(flat.pop("__json__")).decode())
    tree = {}

    def insert(path, value):
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    for key, value in flat.items():
        insert(key, value)
    for key, value in meta.items():
        insert(key, value)
    return tree
