"""Abstract minibatch server.

Re-design of ``veles/loader/base.py`` [U] (SURVEY.md §2.3 "Loader
base"). Semantics preserved: three sample classes served in class order
(TEST=0 → VALID=1 → TRAIN=2) within each epoch; the train class is
reshuffled every epoch with a seeded generator; ``last_minibatch`` fires
on the final minibatch of each class and ``epoch_ended`` on the final
minibatch of the epoch; in distributed runs the loader is the unit whose
master→slave payload is minibatch index ranges (SURVEY.md §3.3).
"""

import numpy

from veles import prng, telemetry
from veles.distributable import IDistributable
from veles.memory import Array
from veles.mutable import Bool
from veles.units import Unit

CLASS_TEST, CLASS_VALID, CLASS_TRAIN = 0, 1, 2
TRIAGE = ("test", "validation", "train")


class Loader(Unit, IDistributable):
    """Base minibatch server unit.

    Subclasses implement :meth:`load_data` (fill ``class_lengths``,
    prepare storage) and :meth:`fill_minibatch` (materialise the rows of
    ``minibatch_indices`` into ``minibatch_data``/``minibatch_labels``).
    """

    negotiates_on_connect = True
    #: True when the whole dataset can live device-resident and
    #: minibatches can be gathered by index on device (enables the
    #: class-scan fast path in XLAStep)
    supports_device_gather = False
    #: True when the loader can materialize minibatch windows on demand
    #: (host decode/augment) for the streaming fast path in XLAStep —
    #: the dataset does NOT need to fit on device; data is shipped in
    #: stacked windows with the metrics fetched once per window
    supports_streaming = False

    def __init__(self, workflow, minibatch_size=100, shuffle=True,
                 prng_key="loader", normalization_type=None,
                 normalization_parameters=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.max_minibatch_size = int(minibatch_size)
        self.shuffle_enabled = bool(shuffle)
        self.prng = prng.get(prng_key)
        #: pluggable input normalizer (SURVEY.md §2.3 "Normalizers");
        #: fitted on TRAIN data, applied per loader subclass
        from veles.normalization import factory
        self.normalizer = factory(normalization_type,
                                  **(normalization_parameters or {}))
        self._normalization_applied = False

        #: samples per class: [test, valid, train]
        self.class_lengths = [0, 0, 0]
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        #: regression targets (MSE workflows); empty when unused
        self.minibatch_targets = Array()
        self.minibatch_indices = Array()
        #: number of *valid* (non-padding) rows in the current minibatch
        self.minibatch_size = 0
        self.minibatch_class = CLASS_TRAIN
        self.minibatch_offset = 0

        #: set by XLAStep in scan mode: host minibatch filling is
        #: skipped (the device gathers rows itself)
        self.device_gather = False

        self.epoch_number = 0
        self.epoch_ended = Bool(False)
        self.last_minibatch = Bool(False)
        #: live gate mirror: True while serving train minibatches (GD
        #: units' gate_skip is its inverse)
        self.train_phase = Bool(True)

        # epoch iteration state
        self._order = []          # [(cls, ndarray-of-global-indices)]
        self._cls_pos = 0
        self._idx_pos = 0

        # distributed: master-side queue of pending (cls, lo, hi) jobs
        self._pending_jobs = []
        self._inflight = {}

        # telemetry: epoch counter/gauge plus per-class minibatch and
        # sample counters (samples-per-second = rate() over the scrape;
        # bench.py reads its throughput rows from these same counters)
        self._tele_epochs = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_loader_epochs_total", "Epochs served",
                ("loader",)).labels(self.name))
        self._tele_epoch_gauge = telemetry.LazyChild(
            lambda: telemetry.gauge(
                "veles_loader_epoch", "Current epoch number",
                ("loader",)).labels(self.name))
        self._tele_serve = {}     # cls -> (minibatches, samples)

    # -- to be implemented by subclasses ------------------------------

    def load_data(self):
        """Discover the dataset: set class_lengths, allocate storage."""
        raise NotImplementedError

    def create_minibatch_data(self):
        """Allocate ``minibatch_data`` (padded to max_minibatch_size)."""
        raise NotImplementedError

    def fill_minibatch(self):
        """Fill minibatch arrays for ``minibatch_indices[:minibatch_size]``."""
        raise NotImplementedError

    # -- derived quantities -------------------------------------------

    @property
    def total_samples(self):
        return int(sum(self.class_lengths))

    def class_offset(self, cls):
        return int(sum(self.class_lengths[:cls]))

    @property
    def effective_batches_per_epoch(self):
        mb = self.max_minibatch_size
        return sum((n + mb - 1) // mb for n in self.class_lengths)

    # -- lifecycle -----------------------------------------------------

    def apply_normalization(self):
        """Fit + apply ``self.normalizer`` (subclass hook). The base
        FAILS LOUDLY when a normalizer was configured on a loader that
        has no implementation — a silently-dropped normalization_type
        would train on raw data without warning."""
        from veles.normalization import NoneNormalizer
        if not isinstance(self.normalizer, NoneNormalizer):
            raise NotImplementedError(
                "%s does not implement pluggable normalization "
                "(normalization_type=%r); use a full-batch loader or "
                "normalize in load_data/fill_minibatch"
                % (type(self).__name__, self.normalizer.NAME))

    def initialize(self, **kwargs):
        super().initialize(**kwargs)
        if self.total_samples == 0:
            self.load_data()
        if self.total_samples == 0:
            raise ValueError("%s loaded an empty dataset" % self.name)
        if not self._normalization_applied:   # idempotent on resume
            self.apply_normalization()
            self._normalization_applied = True
        self.create_minibatch_data()
        if not self.minibatch_indices:
            self.minibatch_indices.reset(
                numpy.zeros(self.max_minibatch_size, dtype=numpy.int32))
        self._start_epoch(first=True)

    def _class_indices(self, cls):
        off = self.class_offset(cls)
        idx = numpy.arange(off, off + self.class_lengths[cls],
                           dtype=numpy.int32)
        if cls == CLASS_TRAIN and self.shuffle_enabled:
            idx = idx[self.prng.permutation(len(idx))]
        return idx

    def _generate_order(self):
        return [(cls, self._class_indices(cls))
                for cls in (CLASS_TEST, CLASS_VALID, CLASS_TRAIN)
                if self.class_lengths[cls] > 0]

    def _start_epoch(self, first=False):
        if first:
            # fresh run / resume: any pre-generated orders are stale
            self._future_orders = []
        else:
            self.epoch_number += 1
            self._tele_epochs.get().inc()
        self._tele_epoch_gauge.get().set(self.epoch_number)
        future = getattr(self, "_future_orders", None)
        if not first and future:
            # consume the order peek_epoch_orders pre-generated (the
            # multi-epoch dispatch path); the PRNG already advanced
            self._order = future.pop(0)
        else:
            self._order = self._generate_order()
        self._cls_pos = 0
        self._idx_pos = 0

    def peek_epoch_orders(self, n):
        """Orders for the current epoch and the next ``n-1``, cached so
        subsequent ``_start_epoch`` calls serve EXACTLY these (shuffles
        come from the same PRNG stream in the same sequence — a chunked
        run is bit-identical to an unchunked one). Enables XLAStep to
        compile several epochs into one device program."""
        if not hasattr(self, "_future_orders"):
            self._future_orders = []
        while len(self._future_orders) < n - 1:
            self._future_orders.append(self._generate_order())
        return [self._order] + self._future_orders[:n - 1]

    # -- serving -------------------------------------------------------

    @staticmethod
    def pad_indices(chunk, size):
        """THE static-shape padding convention, used identically by the
        per-step and scan paths: pad rows repeat the last index (and
        evaluators mask rows past the true count)."""
        padded = numpy.empty(size, dtype=numpy.int32)
        padded[:len(chunk)] = chunk
        if len(chunk) < size:
            padded[len(chunk):] = chunk[-1] if len(chunk) else 0
        return padded

    def _serve_chunk(self, cls, chunk):
        """Publish one minibatch: class/gates bookkeeping + padding."""
        self.minibatch_class = cls
        self.train_phase << (cls == CLASS_TRAIN)
        self.minibatch_size = len(chunk)
        tele = self._tele_serve.get(cls)
        if tele is None:
            cname = TRIAGE[cls]
            tele = self._tele_serve[cls] = (
                telemetry.LazyChild(
                    lambda n=cname: telemetry.counter(
                        "veles_loader_minibatches_total",
                        "Minibatches served", ("loader", "cls"))
                    .labels(self.name, n)),
                telemetry.LazyChild(
                    lambda n=cname: telemetry.counter(
                        "veles_loader_samples_total",
                        "Samples served", ("loader", "cls"))
                    .labels(self.name, n)))
        tele[0].get().inc()
        tele[1].get().inc(len(chunk))
        self.minibatch_indices.map_invalidate()
        self.minibatch_indices.mem[...] = self.pad_indices(
            chunk, self.max_minibatch_size)
        if not self.device_gather:
            self.fill_minibatch()

    def class_schedule(self, cls, order=None):
        """(idx_mat (n_mb, mb) int32, valids (n_mb,) int32) — the full
        minibatch schedule of ``cls`` for the given epoch order (default:
        the CURRENT epoch; the class-scan fast path consumes a whole
        class in one dispatch)."""
        for c, indices in (self._order if order is None else order):
            if c != cls:
                continue
            mb = self.max_minibatch_size
            n_mb = (len(indices) + mb - 1) // mb
            idx_mat = numpy.empty((n_mb, mb), numpy.int32)
            valids = numpy.empty(n_mb, numpy.int32)
            for i in range(n_mb):
                chunk = indices[i * mb:(i + 1) * mb]
                idx_mat[i] = self.pad_indices(chunk, mb)
                valids[i] = len(chunk)
            return idx_mat, valids
        raise ValueError("class %d not in this epoch's order" % cls)

    # -- streaming fast-path hooks (see XLAStep._dispatch_stream_epoch) --

    def epoch_plan(self):
        """[(cls, idx_mat, valids), ...] for the CURRENT epoch in
        serving order, without advancing serving state."""
        return [(cls, *self.class_schedule(cls))
                for cls, _ in self._order]

    def materialize_window(self, cls, idx_mat):
        """dict name -> (B, mb, ...) host arrays for the given rows of
        minibatch indices (B minibatches). Streaming loaders override
        to decode/augment; the base gathers nothing."""
        raise NotImplementedError(
            "%s does not support streaming" % self.name)

    def xla_batch_transform(self, name, tensor, train=False):
        """Traced per-minibatch transform applied on DEVICE to streamed
        batch tensors (e.g. uint8 -> normalized float, so the host→
        device link carries bytes, not floats). ``train`` distinguishes
        phase-dependent augmentation (mirroring etc. must never touch
        eval minibatches). Default: identity."""
        return tensor

    def run(self):
        self.epoch_ended << False
        self.last_minibatch << False
        if self._cls_pos >= len(self._order):
            self._start_epoch()
        cls, indices = self._order[self._cls_pos]
        mb = self.max_minibatch_size
        lo = self._idx_pos
        hi = min(lo + mb, len(indices))
        self.minibatch_offset = lo
        self._serve_chunk(cls, indices[lo:hi])
        self._idx_pos = hi
        if hi >= len(indices):
            self.last_minibatch << True
            self._cls_pos += 1
            self._idx_pos = 0
            if self._cls_pos >= len(self._order):
                self.epoch_ended << True

    # -- checkpoint support (resume restarts the in-flight epoch) ------

    def get_state(self):
        state = {"epoch_number": self.epoch_number,
                 "prng_state": dict(self.prng._gen.bit_generator.state)}
        norm = self.normalizer.state()
        if norm:
            # fitted input statistics ride the checkpoint so an
            # inference-only restore (no train data to re-fit from)
            # still normalizes identically
            state["normalizer"] = norm
        return state

    def set_state(self, state):
        self.epoch_number = int(state["epoch_number"])
        self.prng._gen.bit_generator.state = state["prng_state"]
        norm = state.get("normalizer")
        if norm:
            name = norm.get("__name__")
            if name and name != self.normalizer.NAME:
                # the checkpoint's normalizer wins over the (possibly
                # default) loader config — silently grafting fitted
                # stats onto the wrong class would skip normalization
                from veles.normalization import from_state
                self.warning(
                    "restoring %r normalizer from checkpoint "
                    "(loader was configured with %r)",
                    name, self.normalizer.NAME)
                self.normalizer = from_state(norm)
            else:
                self.normalizer.set_state(norm)
        # restart the in-flight epoch (snapshots happen at the valid/
        # train boundary; replaying the epoch's eval classes is cheap)
        self._start_epoch(first=True)

    # -- IDistributable: ship minibatch index ranges (SURVEY.md §3.3) --

    def generate_data_for_slave(self, slave=None):
        """Pop the next minibatch job; ``None`` signals the epoch's job
        queue is exhausted (the master then aggregates the epoch and
        calls :meth:`master_start_epoch` for the next one)."""
        if not self._pending_jobs:
            return None
        job = self._pending_jobs.pop(0)
        self._inflight.setdefault(slave, []).append(job)
        return job

    def _ensure_dist_prng(self):
        """The master-side shuffle stream, created on first use — ONE
        place owns the derivation, so epoch start and master-restart
        restore (server.py) can never drift apart."""
        if not hasattr(self, "_dist_prng"):
            from veles.prng import RandomGenerator
            self._dist_prng = RandomGenerator(
                "%s.dist" % self.name, self.prng.state_seed + 0x9E3779B9)
        return self._dist_prng

    def master_start_epoch(self):
        """Master side: (re)fill the job queue for one epoch. Uses a
        dedicated generator derived from the loader seed, so master-mode
        shuffles never desynchronize the local serving PRNG (fixed-seed
        reproducibility contract)."""
        self._ensure_dist_prng()
        mb = self.max_minibatch_size
        for cls in (CLASS_TEST, CLASS_VALID, CLASS_TRAIN):
            if self.class_lengths[cls] == 0:
                continue
            off = self.class_offset(cls)
            indices = numpy.arange(off, off + self.class_lengths[cls],
                                   dtype=numpy.int32)
            if cls == CLASS_TRAIN and self.shuffle_enabled:
                indices = indices[self._dist_prng.permutation(len(indices))]
            for lo in range(0, len(indices), mb):
                self._pending_jobs.append(
                    (cls, indices[lo:lo + mb].tolist()))

    def apply_data_from_master(self, data):
        if data is None:
            return
        cls, idx_list = data
        self._serve_chunk(cls, numpy.asarray(idx_list, dtype=numpy.int32))

    def generate_data_for_master(self):
        return None

    def apply_data_from_slave(self, data, slave=None):
        if slave in self._inflight and self._inflight[slave]:
            self._inflight[slave].pop(0)

    def drop_slave(self, slave=None):
        """Re-queue in-flight minibatches of a dead slave (§5.3);
        -> how many were requeued."""
        jobs = self._inflight.pop(slave, [])
        for job in jobs:
            self._pending_jobs.insert(0, job)
        return len(jobs)
