"""Data loading layer (SURVEY.md §2.3).

Re-design of ``veles/loader/`` [U]: the :class:`Loader` unit serves
minibatches of the three sample classes (TEST=0, VALID=1, TRAIN=2) with
seeded per-epoch shuffling, and exposes the epoch bookkeeping ``Bool``s
(``epoch_ended`` / ``last_minibatch``) the Decision unit consumes.

TPU adaptation: minibatches are always *padded to a static
``max_minibatch_size``* (XLA wants static shapes; SURVEY.md §7 "Design
stance"), with the true row count published as ``minibatch_size`` so
evaluators mask padding. The numpy oracle uses the identical padding so
both backends see the same numbers.
"""

from veles.loader.base import (  # noqa: F401
    CLASS_TEST, CLASS_VALID, CLASS_TRAIN, TRIAGE,
    Loader,
)
from veles.loader.fullbatch import FullBatchLoader  # noqa: F401
