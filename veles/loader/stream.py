"""Streaming loaders — datasets that do NOT live device-resident.

The reference's ImageNet-tier loaders stream from disk with host-side
augmentation (SURVEY.md §2.3 "Image loaders", §7 stage 6 "host async
prefetch + device_put double-buffering"). The TPU translation is the
XLAStep streaming mode: the loader materializes WINDOWS of stacked
minibatches on the host (decode/augment in a thread pool, overlapped
with device compute), XLAStep ships each window up once (cheap: the
tunnel uplink is fast, and image data travels as uint8) and runs a
compiled scan over the window's minibatches; metrics come back in one
fetch per window.

This module provides the array-backed base used directly for synthetic
benchmarks and as the machinery under ``veles.loader.image``, plus the
continual-training ingest tier (ISSUE 16): a :class:`StreamSource`
(seekable sample feed), :class:`ContinualStreamLoader` (bounded
async host-side prefetch through a daemon producer thread, per-round
stream cursor, per-slave shard assignment over the lease machinery)
— the input half of the ``veles/continual.py`` closed loop. Device
double-buffering for the windows this loader stages lives in
``XLAStep._put_window`` (one upload in flight, overlapped with the
previous window's compute).
"""

import concurrent.futures
import threading
import time

import numpy

from veles import telemetry
from veles.loader.base import (CLASS_TEST, CLASS_VALID, CLASS_TRAIN,
                               Loader)


class StreamLoader(Loader):
    """Streams minibatch windows; subclasses produce individual samples.

    Contract: implement :meth:`load_data` (set ``class_lengths``) and
    :meth:`materialize_samples` (global indices -> dict of per-sample
    arrays). Decoding parallelism and window stacking live here.
    """

    supports_streaming = True
    #: True when materialize_samples is vectorized numpy (GIL-bound):
    #: the window is produced in ONE call — fanning rows out to decode
    #: threads only adds GIL thrash. File/image loaders (whose decode
    #: releases the GIL inside the codec) leave this False.
    window_vectorized = False

    def __init__(self, workflow, prefetch_workers=8, **kwargs):
        super().__init__(workflow, **kwargs)
        self.prefetch_workers = int(prefetch_workers)
        self._pool = None

    @property
    def pool(self):
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.prefetch_workers,
                thread_name_prefix="%s-decode" % self.name)
        return self._pool

    # -- subclass surface ---------------------------------------------

    def materialize_samples(self, indices, train=None):
        """dict name -> (len(indices), ...) host arrays for the given
        GLOBAL sample indices. ``train`` carries the phase of the
        CLASS being materialized: the fused dispatch builds every
        window of an epoch up front, so ``self.train_phase`` (the
        live serving gate) must NOT be consulted there — None means
        "derive from train_phase" (the per-serve oracle path)."""
        raise NotImplementedError

    def sample_spec(self):
        """dict name -> (shape, dtype) of ONE sample, used to allocate
        the (never host-filled) minibatch template Arrays."""
        raise NotImplementedError

    # -- Loader plumbing ----------------------------------------------

    def create_minibatch_data(self):
        spec = self.sample_spec()
        shape, dtype = spec["data"]
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + tuple(shape), dtype))
        if "labels" in spec:
            lshape, ldtype = spec["labels"]
            self.minibatch_labels.reset(numpy.zeros(
                (self.max_minibatch_size,) + tuple(lshape), ldtype))
        if "targets" in spec:
            tshape, tdtype = spec["targets"]
            self.minibatch_targets.reset(numpy.zeros(
                (self.max_minibatch_size,) + tuple(tshape), tdtype))

    def fill_minibatch(self):
        """Host path (numpy oracle / per-step mode): materialize just
        this minibatch."""
        idx = self.minibatch_indices.mem[:self.minibatch_size]
        batch = self.materialize_samples(numpy.asarray(idx))
        pad = self.max_minibatch_size - len(idx)
        for name, arr in batch.items():
            target = {"data": self.minibatch_data,
                      "labels": self.minibatch_labels,
                      "targets": self.minibatch_targets}[name]
            target.map_invalidate()
            target.mem[:len(idx)] = arr
            if pad:
                target.mem[len(idx):] = arr[-1:]

    def materialize_window(self, cls, idx_mat):
        """Stack B minibatches: one vectorized call over the whole
        window when the producer is numpy-bound, else decode rows in
        the thread pool (one future per minibatch)."""
        train = cls == CLASS_TRAIN
        idx_mat = numpy.asarray(idx_mat)
        if self.window_vectorized:
            b, mb = idx_mat.shape
            flat = self.materialize_samples(idx_mat.reshape(-1),
                                            train=train)
            return {name: arr.reshape((b, mb) + arr.shape[1:])
                    for name, arr in flat.items()}
        futures = [self.pool.submit(self.materialize_samples, row,
                                    train)
                   for row in idx_mat]
        batches = [f.result() for f in futures]
        return {name: numpy.stack([b[name] for b in batches])
                for name in batches[0]}


class ArrayStreamLoader(StreamLoader):
    """Streaming view over in-memory arrays (synthetic benchmarks, and
    the honest stand-in for 'dataset too big for HBM' testing: nothing
    is device-resident; every window travels the host→device link)."""

    window_vectorized = True

    def __init__(self, workflow, data=None, labels=None, targets=None,
                 class_lengths=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self._data = data
        self._labels = labels
        self._targets = targets
        if class_lengths is not None:
            self.class_lengths = list(class_lengths)

    def load_data(self):
        if self._data is None:
            raise ValueError("%s: data unset" % self.name)

    def sample_spec(self):
        spec = {"data": (self._data.shape[1:], self._data.dtype)}
        if self._labels is not None:
            spec["labels"] = (self._labels.shape[1:], self._labels.dtype)
        if self._targets is not None:
            spec["targets"] = (self._targets.shape[1:],
                               self._targets.dtype)
        return spec

    def materialize_samples(self, indices, train=None):
        out = {"data": self._data[indices]}
        if self._labels is not None:
            out["labels"] = self._labels[indices]
        if self._targets is not None:
            out["targets"] = self._targets[indices]
        return out


# -- continual ingest (ISSUE 16) ---------------------------------------


class StreamSource:
    """A seekable, unbounded sample feed: the ingest side of the
    continual loop. ``fetch(start, count)`` may BLOCK until the
    requested positions exist (a stalled upstream is exactly the
    staleness-SLO scenario) and must be safe to call for any already-
    produced position — resume and shard takeover both re-fetch."""

    def spec(self):
        """dict name -> (per-sample shape tuple, dtype)."""
        raise NotImplementedError

    def fetch(self, start, count):
        """dict name -> (count, ...) host arrays for stream positions
        ``[start, start + count)``."""
        raise NotImplementedError

    def close(self):
        pass


class ArraySource(StreamSource):
    """In-memory source cycling over fixed arrays — the synthetic
    stand-in for an endless feed (position ``p`` serves row
    ``p % len(data)``), and the deterministic backend behind the
    chaos tests' HTTP ingest."""

    def __init__(self, data, labels=None, targets=None):
        self._arrays = {"data": numpy.asarray(data)}
        if labels is not None:
            self._arrays["labels"] = numpy.asarray(labels)
        if targets is not None:
            self._arrays["targets"] = numpy.asarray(targets)

    def spec(self):
        return {name: (arr.shape[1:], arr.dtype)
                for name, arr in self._arrays.items()}

    def fetch(self, start, count):
        n = len(self._arrays["data"])
        idx = numpy.arange(start, start + count, dtype=numpy.int64) % n
        return {name: arr[idx] for name, arr in self._arrays.items()}


class ContinualStreamLoader(StreamLoader):
    """Endless stream served as fixed-size training ROUNDS.

    Each epoch ("round") consumes the next ``round_samples`` stream
    positions; a small pinned validation set (the stream's first
    ``valid_samples`` positions) judges improvement so the snapshot
    gate keeps working. Global train index ``g`` maps statelessly to
    stream position ``g - class_offset(CLASS_TRAIN)`` — indices are
    self-describing, so master→slave jobs need no cursor sync and a
    job replayed after restart re-fetches the same samples.

    Host-side prefetch: a daemon producer thread pulls blocks of
    ``max_minibatch_size`` samples from the source into a bounded
    position-keyed buffer (at most ``prefetch_blocks`` resident, the
    producer blocks when full), so decode/transport overlaps device
    compute and the dataset never needs to fit in memory. Reads grab
    references under the lock and assemble outside it — safe under
    XLAStep's concurrent (depth-2) window staging.

    Checkpoint state carries the stream cursor: a resumed run
    continues at the next round's first position — no replay, no
    skip (mid-round snapshots restart the in-flight round, the same
    contract as the base loader's in-flight epoch).
    """

    window_vectorized = True

    def __init__(self, workflow, source=None, round_samples=1024,
                 valid_samples=0, shards=1, prefetch_blocks=16,
                 fetch_retry_s=0.5, **kwargs):
        kwargs.setdefault("shuffle", False)   # stream order IS the order
        super().__init__(workflow, **kwargs)
        self.source = source
        self.round_samples = int(round_samples)
        self.valid_samples = int(valid_samples)
        #: shard partitions per round (master mode): train job k goes
        #: to the slave holding shard ``(first_index // mb) % shards``
        self.shards = max(1, int(shards))
        self.prefetch_blocks = max(2, int(prefetch_blocks))
        self.fetch_retry_s = float(fetch_retry_s)
        #: absolute stream position where the CURRENT round starts
        #: (advances by round_samples the moment a round's last
        #: minibatch is served — an epoch-boundary checkpoint resumes
        #: at the next round)
        self.cursor_base = None
        #: wall time the newest sample arrived from the source — the
        #: ingest clock the staleness SLO measures against
        #: (veles/continual.py stamps it into checkpoint MANIFESTs)
        self.last_ingest_wall = 0.0
        self._valid = None
        self._gen_ahead = 0
        # prefetch plane (all guarded by _cond)
        self._cond = threading.Condition()
        self._blocks = {}            # block id -> dict name -> arrays
        self._next_block = None
        self._demand_block = -1
        self._served_floor = 0       # positions below this are done
        self._producer = None
        self._producer_stop = False
        self._reset_seq = 0
        # lease machinery: distinct slave identity -> shard index
        self._slave_shards = {}
        self._tele_fetch_failures = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_stream_fetch_failures_total",
                "Ingest-source fetches that failed and were retried "
                "(a stalled stream grows this while staleness climbs)",
                ("loader",)).labels(self.name))
        self._tele_buffer = telemetry.LazyChild(
            lambda: telemetry.gauge(
                "veles_stream_prefetch_blocks",
                "Sample blocks resident in the prefetch buffer",
                ("loader",)).labels(self.name))

    # -- dataset shape -------------------------------------------------

    @property
    def block_samples(self):
        return self.max_minibatch_size

    def load_data(self):
        if self.source is None:
            raise ValueError("%s: source unset" % self.name)
        if self.valid_samples:
            self._valid = self.source.fetch(0, self.valid_samples)
            with self._cond:
                self.last_ingest_wall = time.time()
        self.class_lengths = [0, self.valid_samples,
                              self.round_samples]
        if self.cursor_base is None:
            # fresh start: the stream's head fed the validation set
            self.cursor_base = self.valid_samples

    def sample_spec(self):
        return {name: (tuple(shape), numpy.dtype(dtype))
                for name, (shape, dtype) in self.source.spec().items()}

    # -- round scheduling ----------------------------------------------

    def _generate_order(self):
        order = []
        for cls in (CLASS_TEST, CLASS_VALID):
            if self.class_lengths[cls] > 0:
                order.append((cls, self._class_indices(cls)))
        off = self.class_offset(CLASS_TRAIN)
        start = self.cursor_base + self._gen_ahead * self.round_samples
        # int32: the minibatch plumbing's index dtype — a ~2.1e9
        # lifetime sample ceiling, loudly enforced
        if start + self.round_samples + off > numpy.iinfo(numpy.int32).max:
            raise OverflowError(
                "%s: stream position %d overflows the int32 index "
                "plumbing" % (self.name, start + self.round_samples))
        order.append((CLASS_TRAIN, numpy.arange(
            off + start, off + start + self.round_samples,
            dtype=numpy.int32)))
        self._gen_ahead += 1
        return order

    def _start_epoch(self, first=False):
        if first:
            self._gen_ahead = 0
        super()._start_epoch(first)

    def run(self):
        super().run()
        if bool(self.epoch_ended):
            # the round's stream window is consumed the moment its
            # last minibatch is served: an epoch-boundary checkpoint
            # resumes at the NEXT round
            self.cursor_base += self.round_samples
            self._gen_ahead = max(0, self._gen_ahead - 1)

    # -- prefetch plane ------------------------------------------------

    def _ensure_producer(self, first_block):
        if self._producer is not None and self._producer.is_alive():
            return
        if self._next_block is None:
            self._next_block = int(first_block)
        self._producer_stop = False
        self._producer = threading.Thread(
            target=self._produce, args=(self._reset_seq,),
            daemon=True, name="%s-ingest" % self.name)
        self._producer.start()

    def _produce(self, seq):
        bs = self.block_samples
        while True:
            with self._cond:
                while (not self._producer_stop
                       and seq == self._reset_seq
                       and len(self._blocks) >= self.prefetch_blocks
                       and self._next_block > self._demand_block):
                    self._cond.wait(1.0)
                if self._producer_stop or seq != self._reset_seq:
                    return
                block = self._next_block
            try:
                batch = self.source.fetch(block * bs, bs)
            except Exception as exc:
                self._tele_fetch_failures.get().inc()
                self.warning("ingest fetch @%d failed (%s: %s) — "
                             "retrying", block * bs,
                             type(exc).__name__, exc)
                time.sleep(self.fetch_retry_s)
                continue
            with self._cond:
                if self._producer_stop or seq != self._reset_seq:
                    return
                self._blocks[block] = batch
                self._next_block = block + 1
                self.last_ingest_wall = time.time()
                self._tele_buffer.get().set(len(self._blocks))
                self._cond.notify_all()

    def _gather_stream(self, positions):
        bs = self.block_samples
        needed = sorted({int(p) // bs for p in positions})
        with self._cond:
            self._ensure_producer(needed[0])
            self._demand_block = max(self._demand_block, needed[-1])
            self._cond.notify_all()
            while True:
                if self._producer_stop:
                    raise RuntimeError("%s stopped while a window was "
                                       "being materialized" % self.name)
                if all(b in self._blocks for b in needed):
                    break
                self._cond.wait(1.0)
                self._ensure_producer(needed[0])
            grabbed = {b: self._blocks[b] for b in needed}
            # forward-only stream: once grabbed (local refs keep the
            # arrays alive), positions at or below this window's top
            # are never demanded again — evict fully-passed blocks
            self._served_floor = max(self._served_floor,
                                     int(positions.max()) + 1)
            floor_block = self._served_floor // bs
            for b in [b for b in self._blocks if b < floor_block]:
                del self._blocks[b]
            self._tele_buffer.get().set(len(self._blocks))
            self._cond.notify_all()
        names = next(iter(grabbed.values())).keys()
        return {name: numpy.stack(
            [grabbed[int(p) // bs][name][int(p) % bs]
             for p in positions])
            for name in names}

    def materialize_samples(self, indices, train=None):
        indices = numpy.asarray(indices)
        off = self.class_offset(CLASS_TRAIN)
        if len(indices) and int(indices[0]) < off:
            # windows are per class: the whole request is the pinned
            # validation set
            return {name: arr[indices]
                    for name, arr in self._valid.items()}
        return self._gather_stream(indices.astype(numpy.int64) - off)

    def stop(self):
        with self._cond:
            self._producer_stop = True
            self._cond.notify_all()
        super().stop()

    # -- checkpoint: the stream cursor ---------------------------------

    def get_state(self):
        state = super().get_state()
        state["stream_cursor"] = {
            "cursor_base": int(self.cursor_base or 0),
            "ingest_wall": float(self.last_ingest_wall),
        }
        return state

    def set_state(self, state):
        cursor = state.get("stream_cursor")
        if cursor:
            with self._cond:
                self.cursor_base = int(cursor["cursor_base"])
                self.last_ingest_wall = float(
                    cursor.get("ingest_wall", 0.0))
                # drop buffered blocks from the pre-restore position;
                # in-flight producer inserts are fenced by the seq
                self._reset_seq += 1
                self._blocks.clear()
                self._next_block = None
                self._demand_block = -1
                self._served_floor = int(self.cursor_base)
                self._cond.notify_all()
        super().set_state(state)

    # -- per-slave shard assignment (lease machinery) ------------------

    def _job_shard(self, job):
        """Shard of a pending job, derived from CONTENT (the absolute
        first index), so the master's persist/restore path — which
        round-trips plain ``(cls, idx_list)`` pairs — keeps working."""
        cls, idx = job
        if cls != CLASS_TRAIN or self.shards <= 1 or not idx:
            return None
        return (int(idx[0]) // self.max_minibatch_size) % self.shards

    def _shard_for(self, slave):
        shard = self._slave_shards.get(slave)
        if shard is None:
            used = set(self._slave_shards.values())
            free = [s for s in range(self.shards) if s not in used]
            shard = free[0] if free \
                else len(self._slave_shards) % self.shards
            self._slave_shards[slave] = shard
            self.info("stream shard %d/%d -> slave %s", shard,
                      self.shards, slave)
            telemetry.record_event("stream_shard_assigned",
                                   loader=self.name, slave=str(slave),
                                   shard=shard, shards=self.shards)
        return shard

    def master_start_epoch(self):
        mb = self.max_minibatch_size
        for cls in (CLASS_TEST, CLASS_VALID):
            if self.class_lengths[cls] == 0:
                continue
            off = self.class_offset(cls)
            indices = numpy.arange(off, off + self.class_lengths[cls],
                                   dtype=numpy.int32)
            for lo in range(0, len(indices), mb):
                self._pending_jobs.append(
                    (cls, indices[lo:lo + mb].tolist()))
        off = self.class_offset(CLASS_TRAIN)
        start = int(self.cursor_base)
        for lo in range(0, self.round_samples, mb):
            hi = min(lo + mb, self.round_samples)
            self._pending_jobs.append(
                (CLASS_TRAIN, [off + start + j for j in range(lo, hi)]))
        # queue filled == round claimed: the master persist that
        # follows the epoch carries the NEXT round's cursor, and the
        # in-flight jobs it folds back re-serve this one exactly once
        self.cursor_base = start + self.round_samples

    def generate_data_for_slave(self, slave=None):
        if not self._pending_jobs:
            return None
        shard = self._shard_for(slave)
        assigned = set(self._slave_shards.values())
        pick = steal = None
        for i, job in enumerate(self._pending_jobs):
            s = self._job_shard(job)
            if s is None or s == shard:
                pick = i
                break
            if steal is None and s not in assigned:
                steal = i
        if pick is None:
            # shards with no live owner (a slave died or never
            # arrived) must not wedge the round: steal their work
            pick = steal
        if pick is None:
            # someone else's shard — the master answers "wait", the
            # slave polls again
            return None
        job = self._pending_jobs.pop(pick)
        self._inflight.setdefault(slave, []).append(job)
        return job

    def drop_slave(self, slave=None):
        self._slave_shards.pop(slave, None)
        return super().drop_slave(slave)
