"""Streaming loaders — datasets that do NOT live device-resident.

The reference's ImageNet-tier loaders stream from disk with host-side
augmentation (SURVEY.md §2.3 "Image loaders", §7 stage 6 "host async
prefetch + device_put double-buffering"). The TPU translation is the
XLAStep streaming mode: the loader materializes WINDOWS of stacked
minibatches on the host (decode/augment in a thread pool, overlapped
with device compute), XLAStep ships each window up once (cheap: the
tunnel uplink is fast, and image data travels as uint8) and runs a
compiled scan over the window's minibatches; metrics come back in one
fetch per window.

This module provides the array-backed base used directly for synthetic
benchmarks and as the machinery under ``veles.loader.image``.
"""

import concurrent.futures

import numpy

from veles.loader.base import CLASS_TRAIN, Loader


class StreamLoader(Loader):
    """Streams minibatch windows; subclasses produce individual samples.

    Contract: implement :meth:`load_data` (set ``class_lengths``) and
    :meth:`materialize_samples` (global indices -> dict of per-sample
    arrays). Decoding parallelism and window stacking live here.
    """

    supports_streaming = True
    #: True when materialize_samples is vectorized numpy (GIL-bound):
    #: the window is produced in ONE call — fanning rows out to decode
    #: threads only adds GIL thrash. File/image loaders (whose decode
    #: releases the GIL inside the codec) leave this False.
    window_vectorized = False

    def __init__(self, workflow, prefetch_workers=8, **kwargs):
        super().__init__(workflow, **kwargs)
        self.prefetch_workers = int(prefetch_workers)
        self._pool = None

    @property
    def pool(self):
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.prefetch_workers,
                thread_name_prefix="%s-decode" % self.name)
        return self._pool

    # -- subclass surface ---------------------------------------------

    def materialize_samples(self, indices, train=None):
        """dict name -> (len(indices), ...) host arrays for the given
        GLOBAL sample indices. ``train`` carries the phase of the
        CLASS being materialized: the fused dispatch builds every
        window of an epoch up front, so ``self.train_phase`` (the
        live serving gate) must NOT be consulted there — None means
        "derive from train_phase" (the per-serve oracle path)."""
        raise NotImplementedError

    def sample_spec(self):
        """dict name -> (shape, dtype) of ONE sample, used to allocate
        the (never host-filled) minibatch template Arrays."""
        raise NotImplementedError

    # -- Loader plumbing ----------------------------------------------

    def create_minibatch_data(self):
        spec = self.sample_spec()
        shape, dtype = spec["data"]
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + tuple(shape), dtype))
        if "labels" in spec:
            lshape, ldtype = spec["labels"]
            self.minibatch_labels.reset(numpy.zeros(
                (self.max_minibatch_size,) + tuple(lshape), ldtype))
        if "targets" in spec:
            tshape, tdtype = spec["targets"]
            self.minibatch_targets.reset(numpy.zeros(
                (self.max_minibatch_size,) + tuple(tshape), tdtype))

    def fill_minibatch(self):
        """Host path (numpy oracle / per-step mode): materialize just
        this minibatch."""
        idx = self.minibatch_indices.mem[:self.minibatch_size]
        batch = self.materialize_samples(numpy.asarray(idx))
        pad = self.max_minibatch_size - len(idx)
        for name, arr in batch.items():
            target = {"data": self.minibatch_data,
                      "labels": self.minibatch_labels,
                      "targets": self.minibatch_targets}[name]
            target.map_invalidate()
            target.mem[:len(idx)] = arr
            if pad:
                target.mem[len(idx):] = arr[-1:]

    def materialize_window(self, cls, idx_mat):
        """Stack B minibatches: one vectorized call over the whole
        window when the producer is numpy-bound, else decode rows in
        the thread pool (one future per minibatch)."""
        train = cls == CLASS_TRAIN
        idx_mat = numpy.asarray(idx_mat)
        if self.window_vectorized:
            b, mb = idx_mat.shape
            flat = self.materialize_samples(idx_mat.reshape(-1),
                                            train=train)
            return {name: arr.reshape((b, mb) + arr.shape[1:])
                    for name, arr in flat.items()}
        futures = [self.pool.submit(self.materialize_samples, row,
                                    train)
                   for row in idx_mat]
        batches = [f.result() for f in futures]
        return {name: numpy.stack([b[name] for b in batches])
                for name in batches[0]}


class ArrayStreamLoader(StreamLoader):
    """Streaming view over in-memory arrays (synthetic benchmarks, and
    the honest stand-in for 'dataset too big for HBM' testing: nothing
    is device-resident; every window travels the host→device link)."""

    window_vectorized = True

    def __init__(self, workflow, data=None, labels=None, targets=None,
                 class_lengths=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self._data = data
        self._labels = labels
        self._targets = targets
        if class_lengths is not None:
            self.class_lengths = list(class_lengths)

    def load_data(self):
        if self._data is None:
            raise ValueError("%s: data unset" % self.name)

    def sample_spec(self):
        spec = {"data": (self._data.shape[1:], self._data.dtype)}
        if self._labels is not None:
            spec["labels"] = (self._labels.shape[1:], self._labels.dtype)
        if self._targets is not None:
            spec["targets"] = (self._targets.shape[1:],
                               self._targets.dtype)
        return spec

    def materialize_samples(self, indices, train=None):
        out = {"data": self._data[indices]}
        if self._labels is not None:
            out["labels"] = self._labels[indices]
        if self._targets is not None:
            out["targets"] = self._targets[indices]
        return out
