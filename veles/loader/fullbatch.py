"""Whole-dataset-resident loader.

Re-design of ``veles/loader/fullbatch.py`` [U] (SURVEY.md §2.3
"Full-batch loader"): the entire dataset lives in host ``Array``s
(``original_data`` / ``original_labels``); a minibatch is a gather by
indices. Subclasses (or callers) fill the originals in
:meth:`load_data`.
"""

import numpy

from veles.loader.base import Loader
from veles.memory import Array


class FullBatchLoader(Loader):
    """Dataset-in-memory loader; minibatch = row gather."""

    supports_device_gather = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.original_data = Array()
        self.original_labels = Array()
        #: regression targets (MSE workflows; reference FullBatchLoaderMSE)
        self.original_targets = Array()
        #: dtype the minibatch is served in (normalized float input)
        self.serve_dtype = numpy.float32

    def _transform_residents(self):
        """Apply the (fitted) normalizer to the resident data in
        place. Targets are re-pointed only when they ALIAS the data
        buffer (autoencoders); separate regression targets have their
        own feature space, so input statistics must not touch them."""
        data = self.original_data.mem
        aliased = self.original_targets \
            and self.original_targets.mem is data
        self.original_data.mem = self.normalizer.normalize(data)
        if aliased:
            self.original_targets.mem = self.original_data.mem
        self._data_normalized = True

    def apply_normalization(self):
        """Fit the normalizer on the TRAIN rows (the loader layout is
        [test | valid | train]) and transform the resident data —
        eval data never leaks into the statistics."""
        from veles.normalization import NoneNormalizer
        if isinstance(self.normalizer, NoneNormalizer):
            return
        data = self.original_data.mem
        train0 = self.class_offset(2)
        if train0 >= len(data):
            self.warning(
                "no train samples: %s normalization deferred (restore "
                "fitted statistics from a checkpoint for inference)",
                self.normalizer.NAME)
            return
        self.normalizer.analyze(data[train0:])
        self._transform_residents()

    def set_state(self, state):
        super().set_state(state)
        # inference-only restore: the initialize-time fit was deferred
        # (no train rows) — the checkpoint's fitted statistics must
        # now actually transform the resident data
        from veles.normalization import NoneNormalizer
        if not getattr(self, "_data_normalized", False) \
                and not isinstance(self.normalizer, NoneNormalizer):
            self._transform_residents()

    def load_data(self):
        """Default: originals were assigned externally before
        initialize(); subclasses override to actually read a dataset."""
        if not self.original_data:
            raise ValueError(
                "%s: original_data unset and load_data not overridden"
                % self.name)
        if sum(self.class_lengths) == 0:
            raise ValueError(
                "%s: class_lengths must be set with original_data"
                % self.name)
        n = len(self.original_data.mem)
        if n != self.total_samples:
            raise ValueError(
                "%s: %d samples but class_lengths sums to %d"
                % (self.name, n, self.total_samples))

    def create_minibatch_data(self):
        sample_shape = self.original_data.mem.shape[1:]
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + sample_shape, self.serve_dtype))
        if self.original_labels:
            self.minibatch_labels.reset(numpy.zeros(
                (self.max_minibatch_size,)
                + self.original_labels.mem.shape[1:],
                self.original_labels.mem.dtype))
        if self.original_targets:
            self.minibatch_targets.reset(numpy.zeros(
                (self.max_minibatch_size,)
                + self.original_targets.mem.shape[1:],
                self.serve_dtype))

    def device_full_arrays(self, sharding=None):
        """Upload the whole dataset once; returns the dict the
        class-scan gathers minibatches from (keys match XLAStep's
        batch spec names). ``sharding`` places the dataset onto a mesh
        (replicated for DP gathers) instead of a single device."""
        import jax
        if getattr(self, "_device_full_sharding", None) is not sharding:
            self._device_full = None
        if getattr(self, "_device_full", None) is None:
            put = (lambda a: jax.device_put(a, sharding))
            full = {"data": put(
                self.original_data.mem.astype(self.serve_dtype))}
            if self.original_labels:
                full["labels"] = put(self.original_labels.mem)
            if self.original_targets:
                full["targets"] = put(
                    self.original_targets.mem.astype(self.serve_dtype))
            self._device_full = full
            self._device_full_sharding = sharding
        return self._device_full

    def fill_minibatch(self):
        idx = self.minibatch_indices.mem
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[...] = \
            self.original_data.mem[idx].astype(self.serve_dtype)
        if self.original_labels:
            self.minibatch_labels.map_invalidate()
            self.minibatch_labels.mem[...] = self.original_labels.mem[idx]
        if self.original_targets:
            self.minibatch_targets.map_invalidate()
            self.minibatch_targets.mem[...] = \
                self.original_targets.mem[idx].astype(self.serve_dtype)
