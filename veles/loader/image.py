"""Image loaders — directory/file ingestion with augmentation.

Re-design of ``veles/loader/image.py`` / ``file_image.py`` [U]
(SURVEY.md §2.3 "Image loaders"): scale to a target size, random crop +
horizontal mirror for training (center crop, no mirror for eval),
grayscale/RGB color conversion, label-from-path. Decoding runs in the
loader's thread pool (streaming windows overlap the device compute —
see ``veles/loader/stream.py``); images travel to the device as uint8
and are normalized there (``xla_batch_transform``), so the host→device
link carries a quarter of the float bytes.
"""

import os

import numpy

from veles.loader.stream import StreamLoader

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".gif")


class ImageLoaderBase(StreamLoader):
    """Streams decoded+augmented images.

    Parameters (reference knobs [U]):

    * ``scale`` — (h, w) to resize decoded images to (before crop).
    * ``crop`` — (h, w) window cut from the scaled image: random
      position for train minibatches, centered for eval.
    * ``mirror`` — ``"random"`` flips train images with p=0.5 (eval
      never flips); ``False`` disables.
    * ``color_space`` — "RGB" or "GRAY".
    * ``normalize_mean``/``normalize_std`` — device-side f32
      normalization of the uint8 pixels ((x - mean) / std after
      scaling to [0, 1]).
    """

    def __init__(self, workflow, scale=None, crop=None, mirror=False,
                 color_space="RGB", normalize_mean=0.5,
                 normalize_std=0.5, **kwargs):
        super().__init__(workflow, **kwargs)
        self.scale = tuple(scale) if scale else None
        self.crop = tuple(crop) if crop else None
        if mirror not in (False, "random"):
            raise ValueError("mirror must be False or 'random'")
        self.mirror = mirror
        self.color_space = color_space
        self.normalize_mean = float(normalize_mean)
        self.normalize_std = float(normalize_std)
        # augmentation draws are STATELESS per (seed, sample, epoch):
        # decode runs in pool threads, where a shared stateful
        # generator would race; pure derivation keeps fixed-seed
        # reproducibility regardless of thread scheduling, and must
        # not perturb the shuffle stream
        from veles import prng
        self.aug_seed = prng.get(
            kwargs.get("aug_prng_key", "image_augment")).state_seed

    # -- subclass surface ---------------------------------------------

    def decode_image(self, index):
        """uint8 HWC array for GLOBAL sample index (pre-augmentation)."""
        raise NotImplementedError

    def label_of(self, index):
        raise NotImplementedError

    # -- geometry ------------------------------------------------------

    @property
    def channels(self):
        return 1 if self.color_space == "GRAY" else 3

    def sample_shape(self):
        if self.crop:
            return self.crop + (self.channels,)
        if self.scale:
            return self.scale + (self.channels,)
        raise ValueError(
            "%s needs scale= or crop= for a static sample shape"
            % self.name)

    def sample_spec(self):
        return {"data": (self.sample_shape(), numpy.uint8),
                "labels": ((), numpy.int32)}

    # -- decode + augment ---------------------------------------------

    def _to_color(self, img):
        from PIL import Image
        if self.color_space == "GRAY":
            return img.convert("L")
        return img.convert("RGB")

    def _decode_file(self, path):
        from PIL import Image
        with Image.open(path) as img:
            img = self._to_color(img)
            if self.scale:
                img = img.resize((self.scale[1], self.scale[0]),
                                 Image.BILINEAR)
            arr = numpy.asarray(img, numpy.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr

    def _aug_draws(self, index):
        """3 uniforms in [0,1) — crop y, crop x, mirror — pure in
        (aug_seed, sample index, epoch)."""
        gen = numpy.random.Generator(numpy.random.PCG64(
            (self.aug_seed ^ (int(index) * 0x9E3779B1)
             ^ (self.epoch_number * 0x85EBCA6B))
            & 0xFFFFFFFFFFFFFFFF))
        return gen.random(3)

    def _augment(self, arr, train, draws):
        """``draws``: 3 uniforms in [0,1) — crop y, crop x, mirror."""
        ch, cw = self.crop if self.crop else arr.shape[:2]
        h, w = arr.shape[:2]
        if (h, w) != (ch, cw):
            if train:
                y = int(draws[0] * (h - ch + 1))
                x = int(draws[1] * (w - cw + 1))
            else:
                y, x = (h - ch) // 2, (w - cw) // 2
            arr = arr[y:y + ch, x:x + cw]
        if train and self.mirror == "random" and draws[2] < 0.5:
            arr = arr[:, ::-1]
        return arr

    def materialize_samples(self, indices, train=None):
        if train is None:      # per-serve oracle path
            train = bool(self.train_phase)
        shape = self.sample_shape()
        data = numpy.empty((len(indices),) + shape, numpy.uint8)
        labels = numpy.empty(len(indices), numpy.int32)
        for i, idx in enumerate(numpy.asarray(indices)):
            draws = self._aug_draws(idx) if train else None
            arr = self._augment(self.decode_image(int(idx)), train,
                                draws)
            if arr.shape != shape:
                raise ValueError(
                    "%s: decoded %r, expected %r (set scale=)"
                    % (self.name, arr.shape, shape))
            data[i] = arr
            labels[i] = self.label_of(int(idx))
        return {"data": data, "labels": labels}

    def xla_batch_transform(self, name, tensor, train=False):
        if name != "data":
            return tensor
        import jax.numpy as jnp
        mean = self.normalize_mean
        std = max(self.normalize_std, 1e-6)
        return (tensor.astype(jnp.float32) / 255.0 - mean) / std

    def fill_minibatch(self):
        """Host (numpy-oracle) path serves the SAME normalized floats
        the device sees."""
        idx = self.minibatch_indices.mem[:self.minibatch_size]
        batch = self.materialize_samples(numpy.asarray(idx))
        pad = self.max_minibatch_size - len(idx)
        data = (batch["data"].astype(numpy.float32) / 255.0
                - self.normalize_mean) / max(self.normalize_std, 1e-6)
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[:len(idx)] = data
        self.minibatch_labels.map_invalidate()
        self.minibatch_labels.mem[:len(idx)] = batch["labels"]
        if pad:
            self.minibatch_data.mem[len(idx):] = data[-1:]
            self.minibatch_labels.mem[len(idx):] = batch["labels"][-1:]

    def create_minibatch_data(self):
        # the HOST minibatch mirror is float (oracle path); the
        # STREAMED windows stay uint8 (materialize_window path)
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape(),
            numpy.float32))
        self.minibatch_labels.reset(numpy.zeros(
            (self.max_minibatch_size,), numpy.int32))


class FileImageLoader(ImageLoaderBase):
    """Explicit (path, label) lists per class.

    ``test_paths`` / ``valid_paths`` / ``train_paths``: lists of file
    paths; ``labels`` maps path -> int, or pass parallel label lists.
    """

    def __init__(self, workflow, train_paths=(), valid_paths=(),
                 test_paths=(), train_labels=None, valid_labels=None,
                 test_labels=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self._paths = list(test_paths) + list(valid_paths) \
            + list(train_paths)
        self._class_sizes = [len(test_paths), len(valid_paths),
                             len(train_paths)]
        self._label_names = None
        labels = []
        for lst, pths in ((test_labels, test_paths),
                          (valid_labels, valid_paths),
                          (train_labels, train_paths)):
            if lst is None:
                lst = [self.infer_label(p) for p in pths]
            labels.extend(lst)
        self._labels = numpy.asarray(labels, numpy.int32) \
            if labels else numpy.zeros(0, numpy.int32)

    def infer_label(self, path):
        """Default label inference: parent directory name (stable
        sorted mapping built lazily)."""
        return self._dir_label(os.path.basename(os.path.dirname(path)))

    def _dir_label(self, name):
        if self._label_names is None:
            dirs = sorted({os.path.basename(os.path.dirname(p))
                           for p in self._paths})
            self._label_names = {d: i for i, d in enumerate(dirs)}
        return self._label_names[name]

    def load_data(self):
        if not self._paths:
            raise ValueError("%s: no image paths" % self.name)
        self.class_lengths = list(self._class_sizes)

    def decode_image(self, index):
        return self._decode_file(self._paths[index])

    def label_of(self, index):
        return int(self._labels[index])

    @property
    def n_classes(self):
        return int(self._labels.max()) + 1 if len(self._labels) else 0


class AutoLabelFileImageLoader(FileImageLoader):
    """Directory-tree ingestion: ``<base>/<class_name>/*.png``, label =
    class directory (sorted order); a fraction is held out for
    validation (deterministic stride split, so the same tree always
    yields the same split)."""

    def __init__(self, workflow, base_dir=None, valid_ratio=0.1,
                 **kwargs):
        paths_by_class = {}
        for entry in sorted(os.listdir(base_dir)):
            sub = os.path.join(base_dir, entry)
            if not os.path.isdir(sub):
                continue
            files = sorted(
                os.path.join(sub, f) for f in os.listdir(sub)
                if f.lower().endswith(IMAGE_EXTS))
            if files:
                paths_by_class[entry] = files
        if not paths_by_class:
            raise ValueError("no class directories under %r" % base_dir)
        train, valid = [], []
        stride = max(int(round(1.0 / valid_ratio)), 2) \
            if valid_ratio > 0 else 0
        for files in paths_by_class.values():
            for i, p in enumerate(files):
                (valid if stride and i % stride == 0 else train).append(p)
        super().__init__(workflow, train_paths=train,
                         valid_paths=valid, **kwargs)
