"""zlint core: findings, pragmas, the project model, the rule engine.

The engine parses every target file once into a :class:`Module`
(AST + per-line pragma map + import map), assembles them into a
:class:`Project` (cross-module class hierarchy, global-variable type
bindings), and hands the project to each registered rule. Rules are
plain functions ``rule(project) -> [Finding]``; cross-module work
(subclass resolution, the lock graph) goes through the project's
indexes so a rule never re-parses anything.
"""

import ast
import os
import re
import time
import tokenize
from dataclasses import dataclass

#: pragma grammar: ``# zlint: disable=rule-a,rule-b (free-text reason)``
_PRAGMA_RE = re.compile(r"#\s*zlint:\s*disable=([A-Za-z0-9_,-]+)")

#: sanitizer annotation: ``# zlint: sanitizer (free-text reason)`` on
#: (or directly above) a def/class marks it a trusted bounding
#: function / bounded container for the taint rules
_SANITIZER_RE = re.compile(r"#\s*zlint:\s*sanitizer\b")

SEVERITIES = ("error", "warning")


class UnknownRuleError(ValueError):
    """--select named a rule id that is not registered. A dedicated
    type so the CLI's usage-error handling can never swallow a
    rule-internal KeyError as 'unknown rule'."""


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation, anchored at file:line."""

    file: str          # repo-relative (stable for CI diffing)
    line: int
    rule: str
    severity: str
    message: str
    hint: str

    def as_dict(self):
        return {"file": self.file, "line": self.line,
                "rule": self.rule, "severity": self.severity,
                "message": self.message, "hint": self.hint}

    def render(self):
        return "%s:%d: [%s/%s] %s\n    hint: %s" % (
            self.file, self.line, self.severity, self.rule,
            self.message, self.hint)


class ClassInfo:
    """One class definition: bases (simple names), methods, and the
    attribute/lock bindings rules need for cheap type inference."""

    def __init__(self, module, node):
        self.module = module
        self.node = node
        self.name = node.name
        # base simple names: ``veles.units.Unit`` -> ``Unit``
        self.bases = []
        for b in node.bases:
            if isinstance(b, ast.Attribute):
                self.bases.append(b.attr)
            elif isinstance(b, ast.Name):
                self.bases.append(b.id)
        self.methods = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        #: ``self.X = threading.Lock()`` -> {"X": "lock"}; RLock ->
        #: "rlock"; ``Condition(self.Y)`` -> alias recorded separately
        self.locks = {}
        #: Condition built over an existing lock: attr -> aliased attr
        self.lock_aliases = {}
        #: ``self.X = SomeProjectClass(...)`` -> {"X": "SomeProjectClass"}
        self.attr_types = {}
        self._scan_attr_bindings()

    def _scan_attr_bindings(self):
        for meth in self.methods.values():
            for node in ast.walk(meth):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    kind, arg = _lock_ctor(node.value)
                    if kind in ("lock", "rlock"):
                        self.locks[tgt.attr] = kind
                    elif kind == "condition":
                        if arg is not None:
                            self.lock_aliases[tgt.attr] = arg
                        else:
                            self.locks[tgt.attr] = "rlock"
                    elif isinstance(node.value, ast.Call):
                        cname = _call_class_name(node.value)
                        if cname:
                            self.attr_types[tgt.attr] = cname


def _lock_ctor(expr):
    """Classify ``threading.Lock()``-shaped constructor expressions.

    -> ("lock"|"rlock"|"condition", aliased_self_attr_or_None) or
    (None, None)."""
    if not isinstance(expr, ast.Call):
        return None, None
    fn = expr.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name == "Lock":
        return "lock", None
    if name == "RLock":
        return "rlock", None
    if name == "Condition":
        if expr.args and isinstance(expr.args[0], ast.Attribute) \
                and isinstance(expr.args[0].value, ast.Name) \
                and expr.args[0].value.id == "self":
            return "condition", expr.args[0].attr
        return "condition", None
    return None, None


def _call_class_name(call):
    """CapWord constructor calls -> the class simple name."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name and name[:1].isupper():
        return name
    return None


class Module:
    """One parsed source file plus its pragma and import maps."""

    def __init__(self, path, relpath, source):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.pragmas = self._scan_pragmas(source)
        #: line numbers carrying ``# zlint: sanitizer`` annotations
        self.sanitizer_lines = self._scan_sanitizers(source)
        #: local name -> ("module", dotted) | ("symbol", dotted, name)
        self.imports = {}
        #: module-level classes by name
        self.classes = {}
        #: module-level functions by name
        self.functions = {}
        #: module-level ``name = SomeClass(...)`` type bindings and
        #: ``name = threading.Lock()`` global locks
        self.global_types = {}
        self.global_locks = {}
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.imports[local] = ("module", a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:          # relative: not used in veles
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = (
                        "symbol", node.module or "", a.name)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassInfo(self, node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                kind, _ = _lock_ctor(node.value)
                if kind in ("lock", "rlock"):
                    self.global_locks[tname] = kind
                elif isinstance(node.value, ast.Call):
                    cname = _call_class_name(node.value)
                    if cname:
                        self.global_types[tname] = cname

    @staticmethod
    def _scan_pragmas(source):
        """{lineno: set(rule ids) | {"all"}} from zlint comments.

        Tokenize-based so a ``#`` inside a string literal can never
        read as a pragma; falls back to a line regex if tokenization
        chokes (it shouldn't on anything ast.parse accepted)."""
        pragmas = {}
        try:
            tokens = tokenize.generate_tokens(
                iter(source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    pragmas.setdefault(tok.start[0], set()).update(rules)
        except (tokenize.TokenError, IndentationError):
            for i, line in enumerate(source.splitlines(), 1):
                m = _PRAGMA_RE.search(line)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    pragmas.setdefault(i, set()).update(rules)
        return pragmas

    @staticmethod
    def _scan_sanitizers(source):
        """Line numbers annotated ``# zlint: sanitizer`` — tokenize-
        based like the pragma scan, same string-literal immunity."""
        lines = set()
        try:
            tokens = tokenize.generate_tokens(
                iter(source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type == tokenize.COMMENT \
                        and _SANITIZER_RE.search(tok.string):
                    lines.add(tok.start[0])
        except (tokenize.TokenError, IndentationError):
            for i, line in enumerate(source.splitlines(), 1):
                if _SANITIZER_RE.search(line):
                    lines.add(i)
        return lines

    def suppressed(self, line, rule):
        rules = self.pragmas.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


class Project:
    """All modules under analysis + cross-module indexes."""

    def __init__(self, modules):
        self.modules = modules
        #: simple class name -> [ClassInfo] (collisions kept — rules
        #: resolve conservatively over all of them)
        self.class_index = {}
        for mod in modules:
            for info in mod.classes.values():
                self.class_index.setdefault(info.name, []).append(info)
        #: dotted module path -> Module (veles/foo/bar.py -> veles.foo.bar)
        self.module_index = {}
        for mod in modules:
            rel = mod.relpath.replace("\\", "/")
            d = rel[:-3] if rel.endswith(".py") else rel
            d = d[:-9] if d.endswith("/__init__") else d
            self.module_index[d.replace("/", ".")] = mod

    def module_by_dotted(self, dotted):
        return self.module_index.get(dotted)

    def resolve_module_alias(self, mod, local):
        """The project Module a local name refers to, through either
        import form (``import veles.telemetry`` / ``from veles import
        telemetry`` / ``from x import y as z``), or None."""
        target = mod.imports.get(local)
        if target is None:
            return None
        if target[0] == "module":
            return self.module_by_dotted(target[1])
        return self.module_by_dotted("%s.%s" % (target[1], target[2]))

    def _merge_hierarchy(self, info, extract):
        """{key: nearest-definition value} walking ``info`` then its
        resolvable ancestors breadth-first (MRO-ish: own class wins)."""
        out = {}
        seen = set()
        queue = [info]
        while queue:
            cur = queue.pop(0)
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            for key, value in extract(cur).items():
                out.setdefault(key, value)
            for base in cur.bases:
                queue.extend(self.class_index.get(base, ()))
        return out

    def class_methods(self, info):
        """Hierarchy-merged {method name: (owner ClassInfo,
        FunctionDef)} — a thread started by a base class races with a
        subclass's public API exactly like a same-class pair does."""
        return self._merge_hierarchy(
            info, lambda c: {n: (c, f) for n, f in c.methods.items()})

    def class_attr_types(self, info):
        """Hierarchy-merged {attr: class simple name} for
        ``self.X = SomeClass(...)`` bindings."""
        return self._merge_hierarchy(info, lambda c: c.attr_types)

    def is_subclass_of(self, info, root_name):
        """True when ``info`` transitively names ``root_name`` among
        its bases (simple-name resolution — precise enough for one
        package; unresolvable bases end the chain)."""
        seen = set()
        stack = [info]
        while stack:
            cur = stack.pop()
            if cur.name == root_name:
                return True
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            for base in cur.bases:
                if base == root_name:
                    return True
                stack.extend(self.class_index.get(base, ()))
        return False

    def class_locks(self, info):
        """Merged lock bindings over ``info`` AND its resolvable
        ancestors (nearest definition wins): ``({attr: (owner_class,
        kind)}, {attr: aliased_attr})``. A subclass using a lock its
        base bound in ``__init__`` is the NORMAL shape here, so
        per-class-only lookup would blind the concurrency rules."""
        locks = self._merge_hierarchy(
            info, lambda c: {a: (c.name, k)
                             for a, k in c.locks.items()})
        aliases = self._merge_hierarchy(info, lambda c: c.lock_aliases)
        return locks, aliases

    def find_method(self, info, name):
        """The defining (ClassInfo, FunctionDef) for ``name`` on
        ``info`` or its project-resolvable ancestors."""
        seen = set()
        stack = [info]
        while stack:
            cur = stack.pop(0)           # MRO-ish: breadth first
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            if name in cur.methods:
                return cur, cur.methods[name]
            for base in cur.bases:
                stack.extend(self.class_index.get(base, ()))
        return None, None


# -- rule registry -----------------------------------------------------

#: rule id -> (check(project) -> [Finding], severity, one-line doc).
#: Populated by the rules_* modules at import time via register().
RULES = {}

#: rule id -> "module" | "project". A module-scope rule's findings in
#: module M depend only on M plus its transitive imports (and same-
#:name classes) — the incremental cache re-runs it on just the edited
#: module's dependency closure. Project-scope rules (cross-module
#: dataflow: wire-schema, lock cycles, taint) re-run whenever any
#: module changed. Defaults to the conservative "project".
RULE_SCOPES = {}


def register(rule_id, severity, doc, scope="project"):
    if severity not in SEVERITIES:
        raise ValueError("severity must be one of %s" % (SEVERITIES,))
    if scope not in ("module", "project"):
        raise ValueError("scope must be 'module' or 'project'")

    def wrap(fn):
        RULES[rule_id] = (fn, severity, doc)
        RULE_SCOPES[rule_id] = scope
        return fn
    return wrap


def _load_rules():
    # import for registration side effects (keeps RULES the single
    # source the CLI, tests and docs iterate)
    from veles.analysis import (        # noqa: F401
        rules_hygiene, rules_loop, rules_model_stats, rules_probes,
        rules_profiler, rules_purity, rules_reactor, rules_resources,
        rules_state, rules_taint, rules_telemetry, rules_threads,
        rules_wire)


def iter_py_files(paths):
    """Expand files/directories to sorted .py paths (skips caches)."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f)
                           for f in sorted(files) if f.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return sorted(set(out))


def _relpath(path, base):
    ap = os.path.abspath(path)
    if base and ap.startswith(base.rstrip(os.sep) + os.sep):
        return os.path.relpath(ap, base)
    return ap


def build_project(paths, base=None):
    """Parse ``paths`` (files or directories) into a Project.

    ``base`` anchors the repo-relative paths findings carry; default =
    the current directory when the files live under it (stable output
    for CI diffing), absolute paths otherwise."""
    base = os.path.abspath(base or os.getcwd())
    modules = []
    for path in iter_py_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        modules.append(Module(path, _relpath(path, base), source))
    return Project(modules)


def pragma_filtered(project, raw_findings):
    """Drop findings suppressed by a same-line pragma."""
    by_path = {m.relpath: m for m in project.modules}
    out = []
    for f in raw_findings:
        mod = by_path.get(f.file)
        if mod is not None and mod.suppressed(f.line, f.rule):
            continue
        out.append(f)
    return out


def analyze(project, select=None, cache=None, stats=None):
    """Run every (or the selected) registered rule; -> sorted,
    pragma-filtered findings.

    ``cache`` — an :class:`veles.analysis.cache.AnalysisCache` —
    reuses stored per-rule results keyed by content hashes (see that
    module for the invalidation model). ``stats`` — a caller-supplied
    list — receives one dict per rule run: rule id, wall seconds,
    finding count and fresh/cached module counts (``--stats``)."""
    _load_rules()
    if select:
        unknown = set(select) - set(RULES)
        if unknown:
            raise UnknownRuleError("unknown rule(s): %s" % ", ".join(
                sorted(unknown)))
    findings = []
    for rule_id, (fn, _sev, _doc) in sorted(RULES.items()):
        if select and rule_id not in select:
            continue
        t0 = time.perf_counter()
        if cache is not None:
            got, fresh, cached = cache.run_rule(
                project, rule_id, fn,
                RULE_SCOPES.get(rule_id, "project"))
        else:
            got = pragma_filtered(project, fn(project))
            fresh, cached = len(project.modules), 0
        findings.extend(got)
        if stats is not None:
            stats.append({
                "rule": rule_id,
                "seconds": round(time.perf_counter() - t0, 4),
                "findings": len(got),
                "fresh_modules": fresh,
                "cached_modules": cached,
            })
    return sorted(findings)


def analyze_paths(paths, base=None, select=None, cache=None,
                  stats=None):
    """One-call surface: parse + analyze. -> sorted [Finding]."""
    return analyze(build_project(paths, base=base), select=select,
                   cache=cache, stats=stats)
