"""reactor-purity: reactor callbacks must never block the loop.

The process runs ONE selector loop (``veles/reactor.py``) under the
training wire plane, web-status and the serving frontend. Anything
that parks a callback parks EVERY connection, every probe and every
timer with it — the exact failure the thread-per-connection design
could hide (one stuck thread stalled one slave; one stuck callback
stalls the cluster's whole control surface).

This rule finds code that runs ON the loop — methods named
``on_frame``/``on_timer`` (the reactor callback convention) and the
function targets of ``call_soon``/``call_later``/``every`` — and
flags blocking primitives inside them:

* raw-socket waits: ``recv``/``recv_into``/``recvfrom``/``sendall``/
  ``accept``/``create_connection`` (loop callbacks hand bytes to the
  connection's bounded write queue instead);
* ``time.sleep`` (schedule a timer instead);
* thread parking: ``Event.wait``/``Condition.wait``/``Thread.join``
  (``join`` is flagged only in its no-positional-arg / ``timeout=``
  shapes, so ``", ".join(parts)`` stays quiet);
* network fetches: ``urlopen``/``urlretrieve``.

Taking the existing short-lived locks (the master's request lock) is
deliberately NOT flagged: that is the same serialization the
thread-per-connection design had, and the ``lock-order`` rule already
polices the discipline itself.
"""

import ast

from veles.analysis.core import Finding, register

#: reactor scheduling API: the (position of the) callback argument
_SCHEDULE_CALLS = {"call_soon": 0, "call_later": 1, "every": 1}

#: conventional reactor callback method names. on_readable/on_writable
#: are excluded on purpose — they ARE the I/O layer (the one place
#: recv/send on the non-blocking socket is the job).
_CALLBACK_METHODS = frozenset(("on_frame", "on_timer"))

_BLOCKING = frozenset((
    "recv", "recv_into", "recvfrom", "sendall", "accept",
    "create_connection", "sleep", "wait", "urlopen", "urlretrieve",
))


def _call_name(node):
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _blocking_name(node):
    """The banned primitive ``node`` calls, or None. ``join`` needs
    disambiguation: ``Thread.join()``/``Thread.join(timeout=2)`` have
    no positional args while ``str.join`` always takes exactly one —
    the 1-positional-arg spelling is left alone (documented gap:
    ``t.join(5)``)."""
    name = _call_name(node)
    if name in _BLOCKING:
        return name
    if name == "join" and not node.args:
        return name
    return None


def _scan_callback(mod, node, where, findings, seen):
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = _blocking_name(sub)
        if name is None:
            continue
        key = (mod.relpath, sub.lineno, name)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            mod.relpath, sub.lineno, "reactor-purity", "error",
            "blocking call %r inside reactor callback %s — one "
            "parked callback stalls every connection, probe and "
            "timer on the shared loop" % (name, where),
            "hand the wait to a worker thread (reply via call_soon) "
            "or reschedule with a reactor timer; the loop owns "
            "sockets, threads own waiting"))


def _resolve_target(cb, mod, cls_node, func_stack):
    """The FunctionDef/Lambda a scheduling call's callback argument
    names, resolved conservatively: a lambda inline, a Name through
    the enclosing function scopes then module functions, or a
    ``self.method`` on the enclosing class."""
    if isinstance(cb, ast.Lambda):
        return cb, "<lambda>"
    if isinstance(cb, ast.Name):
        for enclosing in reversed(func_stack):
            for sub in ast.walk(enclosing):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == cb.id:
                    return sub, cb.id
        fn = mod.functions.get(cb.id)
        if fn is not None:
            return fn, cb.id
        return None, None
    if isinstance(cb, ast.Attribute) \
            and isinstance(cb.value, ast.Name) \
            and cb.value.id == "self" and cls_node is not None:
        info = mod.classes.get(cls_node.name)
        if info is not None and cb.attr in info.methods:
            return (info.methods[cb.attr],
                    "%s.%s" % (cls_node.name, cb.attr))
    return None, None


def _walk_scopes(node, cls_node, func_stack, out):
    """Collect (call, enclosing class, enclosing function stack) for
    every scheduling call, tracking scope as we descend."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.ClassDef):
            _walk_scopes(child, child, func_stack, out)
            continue
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            _walk_scopes(child, cls_node, func_stack + [child], out)
            continue
        if isinstance(child, ast.Call):
            name = _call_name(child)
            if name in _SCHEDULE_CALLS:
                out.append((child, cls_node, list(func_stack)))
        _walk_scopes(child, cls_node, func_stack, out)


@register("reactor-purity", "error",
          "reactor callbacks (on_frame/on_timer, call_soon/call_later"
          "/every targets) must not call blocking primitives — no "
          "raw-socket recv/sendall/accept, sleep, Event.wait/"
          "Thread.join, urlopen")
def check_reactor_purity(project):
    findings = []
    seen = set()
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and item.name in _CALLBACK_METHODS:
                        _scan_callback(
                            mod, item,
                            "%s.%s" % (node.name, item.name),
                            findings, seen)
        calls = []
        _walk_scopes(mod.tree, None, [], calls)
        for call, cls_node, func_stack in calls:
            pos = _SCHEDULE_CALLS[_call_name(call)]
            if len(call.args) <= pos:
                continue
            target, desc = _resolve_target(
                call.args[pos], mod, cls_node, func_stack)
            if target is not None:
                _scan_callback(mod, target,
                               "%s (scheduled at line %d)"
                               % (desc, call.lineno), findings, seen)
    return findings
