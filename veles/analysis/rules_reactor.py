"""reactor-purity: reactor callbacks must never block the loop.

The process runs ONE selector loop (``veles/reactor.py``) under the
training wire plane, web-status and the serving frontend. Anything
that parks a callback parks EVERY connection, every probe and every
timer with it — the exact failure the thread-per-connection design
could hide (one stuck thread stalled one slave; one stuck callback
stalls the cluster's whole control surface).

This rule finds code that runs ON the loop — via the shared
:func:`veles.analysis.engine.reactor_callbacks` enumeration (methods
named ``on_frame``/``on_timer`` and the function targets of
``call_soon``/``call_later``/``every``/``post``) — and flags blocking
primitives inside them:

* raw-socket waits: ``recv``/``recv_into``/``recvfrom``/``sendall``/
  ``accept``/``create_connection`` (loop callbacks hand bytes to the
  connection's bounded write queue instead);
* ``time.sleep`` (schedule a timer instead);
* thread parking: ``Event.wait``/``Condition.wait``/``Thread.join``
  (``join`` is flagged only in its no-positional-arg / ``timeout=``
  shapes, so ``", ".join(parts)`` stays quiet);
* network fetches: ``urlopen``/``urlretrieve``.

Taking the existing short-lived locks (the master's request lock) is
deliberately NOT flagged: that is the same serialization the
thread-per-connection design had, and the ``lock-order`` rule already
polices the discipline itself.
"""

from veles.analysis import engine
from veles.analysis.core import Finding, register

_BLOCKING = frozenset((
    "recv", "recv_into", "recvfrom", "sendall", "accept",
    "create_connection", "sleep", "wait", "urlopen", "urlretrieve",
))


def _blocking_name(node):
    """The banned primitive ``node`` calls, or None. ``join`` needs
    disambiguation: ``Thread.join()``/``Thread.join(timeout=2)`` have
    no positional args while ``str.join`` always takes exactly one —
    the 1-positional-arg spelling is left alone (documented gap:
    ``t.join(5)``)."""
    name = engine.call_name(node)
    if name in _BLOCKING:
        return name
    if name == "join" and not node.args:
        return name
    return None


def _scan_callback(mod, node, where, findings, seen):
    for sub, name in engine.novel_calls(mod, node, seen,
                                        _blocking_name):
        findings.append(Finding(
            mod.relpath, sub.lineno, "reactor-purity", "error",
            "blocking call %r inside reactor callback %s — one "
            "parked callback stalls every connection, probe and "
            "timer on the shared loop" % (name, where),
            "hand the wait to a worker thread (reply via call_soon) "
            "or reschedule with a reactor timer; the loop owns "
            "sockets, threads own waiting"))


@register("reactor-purity", "error",
          "reactor callbacks (on_frame/on_timer, call_soon/call_later"
          "/every targets) must not call blocking primitives — no "
          "raw-socket recv/sendall/accept, sleep, Event.wait/"
          "Thread.join, urlopen")
def check_reactor_purity(project):
    findings = []
    seen = set()
    for mod, _cls, func, where in engine.reactor_callbacks(project):
        _scan_callback(mod, func, where, findings, seen)
    return findings
