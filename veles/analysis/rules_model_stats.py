"""stats-cadence: in-graph model stats materialize only behind the
cadence gate.

The model-health plane (ISSUE 15, ``veles/model_health.py``) rides a
per-layer stat vector on every compiled step's outputs. The whole
design is ONE fused extra output with host materialization at a
configurable cadence — ``XLAStep._publish_model_stats`` checks
``_stats_due()`` before touching the vectors. A call site that
materializes stat outputs per step (``float()``/``int()``/
``.item()``/``numpy.asarray()``/``.tolist()``) silently reintroduces
a device→host sync on every dispatch — exactly the per-step host
round-trip the XLA redesign exists to eliminate, and invisible in
tests because the values come back correct.

This rule finds **stat-handling functions** — any function that

* mentions the stat-key marker (the ``"stat/"`` string constant or a
  ``STAT_KEY_PREFIX`` name/attribute reference), or
* calls the monitor sink ``observe_stats``

— and, when such a function also calls a materializer, requires it to
consult the cadence gate: reference something whose name contains
``stats_due`` (the gate method/helper), or carry a
``# zlint: disable=stats-cadence (reason)`` pragma. Pure key routing
(``model_health.take_stats``) has no materializers and stays quiet;
the monitor's own ``observe_stats`` body is the sanctioned sink behind
the gate and is exempt by name.
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

#: split so the rule's own source can never match the marker it scans
#: for (same trick as rules_profiler)
_MARKER = "st" + "at/"

#: names whose reference marks a function as stat-handling
_PREFIX_NAMES = frozenset(("STAT_KEY_PREFIX",))

#: the monitor sink: calling it means the function feeds stat vectors
_SINK_CALLS = frozenset(("observe_stats",))

#: host-materialization calls banned outside the cadence gate
_MATERIALIZERS = frozenset((
    "float", "int", "item", "asarray", "array", "tolist", "ravel"))

#: a name/attr containing this fragment counts as consulting the gate
_GATE_FRAGMENT = "stats_due"


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _is_stat_handler(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str) \
                and _MARKER in node.value:
            return True
        if isinstance(node, ast.Name) and node.id in _PREFIX_NAMES:
            return True
        if isinstance(node, ast.Attribute) \
                and node.attr in _PREFIX_NAMES:
            return True
        if isinstance(node, ast.Call) \
                and engine.call_name(node) in _SINK_CALLS:
            return True
    return False


def _consults_gate(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and _GATE_FRAGMENT in node.id:
            return True
        if isinstance(node, ast.Attribute) \
                and _GATE_FRAGMENT in node.attr:
            return True
    return False


@register("stats-cadence", "error",
          "in-graph model-stat outputs materialize on the host only "
          "behind the cadence gate (stats_due), never per step",
          scope="module")
def check_stats_cadence(project):
    findings = []
    for mod in project.modules:
        for fn in _functions(mod.tree):
            if fn.name in _SINK_CALLS:
                # the monitor's own sink: every caller is already
                # forced through the gate by this rule
                continue
            if not _is_stat_handler(fn):
                continue
            if _consults_gate(fn):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and engine.call_name(node) in _MATERIALIZERS:
                    findings.append(Finding(
                        mod.relpath, node.lineno, "stats-cadence",
                        "error",
                        "%r materializes values in a stat-handling "
                        "function (%s) that never consults the "
                        "cadence gate — per-step host sync of "
                        "in-graph stat outputs is the round-trip the "
                        "fused step exists to avoid"
                        % (engine.call_name(node), fn.name),
                        "route the materialization through the "
                        "cadence-gated publish path (guard on "
                        "_stats_due()), or pragma why this site is "
                        "not per-step"))
    return findings
