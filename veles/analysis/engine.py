"""The shared whole-program analysis engine under every rule pack.

Before this module, each rule pack grew its own call resolution —
``rules_threads`` carried ~130 lines of ``self.method``/module-alias/
symbol-import resolution, ``rules_reactor`` its own callback-target
resolver, ``rules_purity`` a third copy specialized to ops helpers —
and none of them could see ACROSS functions in a principled way. This
module factors all of that into one place:

* **name helpers** — :func:`call_name`, :func:`attr_chain`,
  :func:`receiver_name`, :func:`assigned_name`,
  :func:`canonical_import_prefixes`: the small AST spellings every
  rule needs;
* **statement traversal** — :func:`iter_stmt_children` /
  :func:`walk_statements`: child iteration that descends the
  structural carriers (``ExceptHandler``, ``match_case``) whose
  bodies are exactly where retry/error paths live, so no rule grows a
  blind spot there again;
* **:class:`CallGraph`** — interprocedural call resolution over a
  :class:`~veles.analysis.core.Project`: direct calls,
  ``self.method`` (hierarchy-merged), ``self.attr.method`` through
  ``__init__`` type bindings, module-alias and symbol-import calls,
  constructor calls, and module-level instance methods. One resolver,
  one behavior, every rule;
* **reactor-context enumeration** — :func:`reactor_callbacks` /
  :func:`schedule_sites` / :func:`resolve_callable`: the shared
  answer to "which functions run ON the loop" (``on_frame``/
  ``on_timer`` methods plus ``call_soon``/``call_later``/``every``/
  ``post`` targets), used by ``reactor-purity``,
  ``profiler-safety`` and ``loop-exception-safety`` alike;
* **:class:`ForwardDataflow`** — a generic forward fixpoint over the
  call graph: facts seed at entry functions and flow caller→callee
  through a rule-supplied transfer function until no new
  (function, fact) state appears. ``loop-exception-safety`` runs on
  it with caught-exception sets as the lattice;
* **graph utilities** — :func:`tarjan_sccs` (the lock-order cycle
  detector), exception-hierarchy queries (:func:`exception_covered`)
  shared by the dataflow rules.

Everything here is pure AST work over the already-parsed project —
the engine never re-reads a file.
"""

import ast

#: bound on interprocedural walk depth — cycles are caught by the
#: per-walk visited sets, this only caps pathological chains
MAX_DEPTH = 40

# -- name helpers -------------------------------------------------------


def call_name(node):
    """The rightmost simple name a call invokes (``a.b.f()`` -> 'f',
    ``f()`` -> 'f'), or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def attr_chain(expr):
    """Dotted name of an attribute chain (``a.b.c`` -> 'a.b.c'), or
    None when the chain does not root in a plain Name."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def receiver_name(node):
    """The rightmost name of a call receiver: ``a.b.profiler`` ->
    'profiler', ``profiler`` -> 'profiler', else ''."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return receiver_name(node.func)
    return ""


def target_key(t):
    """A comparable key for an assignment target: ``x`` -> "x",
    ``self.x`` -> "self.x", else None."""
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
        return "%s.%s" % (t.value.id, t.attr)
    return None


def assigned_name(mod, call):
    """The Name/self-attribute a constructor call is assigned to, as
    a comparable key ("x" or "self.x"), or None for a bare call."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and node.value is call:
            return target_key(node.targets[0])
    return None


def canonical_import_prefixes(mod):
    """local name -> canonical dotted path, resolving every import
    style (``import numpy as np``, ``from numpy import random``,
    ``from time import monotonic``) so namespace bans cannot be
    dodged by how a module was imported."""
    out = {}
    for local, target in mod.imports.items():
        if target[0] == "module":
            dotted = target[1]
            if "." in dotted and local == dotted.split(".")[0]:
                # plain ``import numpy.random`` binds the TOP package
                # name; the attribute chain spells out the rest
                dotted = local
        else:
            dotted = "%s.%s" % (target[1], target[2])
        out[local] = dotted
    return out


# -- statement traversal ------------------------------------------------


def iter_stmt_children(node):
    """Yield ``("stmt", s)`` / ``("expr", e)`` for the children of a
    statement, descending structural nodes that are neither stmt nor
    expr but CARRY statements (``ExceptHandler``, ``match_case``) —
    their bodies are exactly where retry/error paths live, so
    skipping them silently weakens every rule built on this."""
    for field in ast.iter_child_nodes(node):
        if isinstance(field, ast.stmt):
            yield "stmt", field
        elif isinstance(field, ast.expr):
            yield "expr", field
        else:
            for sub in ast.iter_child_nodes(field):
                if isinstance(sub, ast.stmt):
                    yield "stmt", sub
                elif isinstance(sub, ast.expr):
                    yield "expr", sub


def walk_statements(func):
    """Every statement in ``func``'s body, in source order, WITHOUT
    descending into nested function/class definitions (they execute
    later, not here)."""
    out = []

    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            out.append(stmt)
            for kind, child in iter_stmt_children(stmt):
                if kind == "stmt":
                    walk([child])
    walk(func.body)
    return out


def scoped_nodes(node):
    """Every node under ``node`` that executes in ITS scope — nested
    function/lambda/class subtrees are skipped (they run later,
    elsewhere). The shared spelling of the walk a half-dozen rules
    used to hand-roll."""
    out = []

    def walk(cur):
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda,
                                  ast.ClassDef)):
                continue
            out.append(child)
            walk(child)
    walk(node)
    return out


def iter_calls(node):
    """Call nodes at or under ``node`` that execute in its scope
    (nested def/lambda bodies excluded — a deferred closure's calls
    run on whatever thread runs IT, not here)."""
    out = [node] if isinstance(node, ast.Call) else []
    out.extend(n for n in scoped_nodes(node)
               if isinstance(n, ast.Call))
    return out


def novel_calls(mod, func, seen, classify):
    """Yield ``(call, label)`` for each call in ``func`` that
    ``classify`` recognizes and that has not been reported yet —
    the shared dedup shell of every scan-a-callback rule. ``seen``
    is keyed (relpath, lineno, label) across contexts, so a method
    that is both a conventional callback and a scheduled target is
    reported once."""
    for sub in ast.walk(func):
        if not isinstance(sub, ast.Call):
            continue
        label = classify(sub)
        if label is None:
            continue
        key = (mod.relpath, sub.lineno, label)
        if key in seen:
            continue
        seen.add(key)
        yield sub, label


def test_mentions(test, markers):
    """True when an if-test contains a string constant carrying any
    of ``markers`` — the branch-detection convention route rules key
    on (``==``, ``startswith``, tuple membership: any spelling)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Constant) \
                and isinstance(sub.value, str) \
                and any(m in sub.value for m in markers):
            return True
    return False


def nested_functions(func):
    """{name: FunctionDef} of the function/async defs nested anywhere
    inside ``func`` (excluding ``func`` itself)."""
    out = {}
    for node in ast.walk(func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not func:
            out[node.name] = node
    return out


# -- the interprocedural call graph -------------------------------------


class Target:
    """One resolved callee: where it lives and what to call it in a
    diagnostic chain."""

    __slots__ = ("module", "cls", "func", "label")

    def __init__(self, module, cls, func, label):
        self.module = module    # Module the definition lives in
        self.cls = cls          # ClassInfo or None
        self.func = func        # FunctionDef / AsyncFunctionDef
        self.label = label      # "Class.meth" / "alias.func" / name


class CallGraph:
    """Interprocedural call resolution over a Project.

    One resolver for every rule pack: ``self.method(...)`` through
    the hierarchy-merged method table, ``self.attr.method(...)``
    through ``__init__`` type bindings (base-class bindings
    included), module-alias calls (``telemetry.counter(...)``),
    symbol imports (``from x import f``; also the ``from veles
    import telemetry`` module-through-symbol form), module-level
    functions, constructor calls (resolved to ``__init__``) and
    methods on module-level typed instances. Unresolvable calls
    return None — every rule on this graph is conservative by
    construction."""

    def __init__(self, project):
        self.project = project

    def _module_for(self, dotted):
        return self.project.module_by_dotted(dotted)

    def resolve(self, ctx_mod, ctx_cls, call):
        """-> :class:`Target` or None for one ``ast.Call``."""
        fn = call.func
        # self.method(...)
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name):
            base = fn.value.id
            if base == "self" and ctx_cls is not None:
                cls, meth = self.project.find_method(ctx_cls, fn.attr)
                if meth is not None:
                    return Target(cls.module, cls, meth,
                                  "%s.%s" % (cls.name, fn.attr))
                return None
            # module_alias.func(...) / global_instance.method(...)
            target = ctx_mod.imports.get(base)
            if target and target[0] == "symbol":
                # ``from veles import telemetry`` imports a MODULE
                # through the symbol form — resolve it as one
                if self._module_for("%s.%s" % (target[1], target[2])):
                    target = ("module",
                              "%s.%s" % (target[1], target[2]))
            if target and target[0] == "module":
                mod = self._module_for(target[1])
                if mod and fn.attr in mod.functions:
                    return Target(mod, None, mod.functions[fn.attr],
                                  "%s.%s" % (base, fn.attr))
                if mod and fn.attr in mod.classes:
                    cls = mod.classes[fn.attr]
                    ini = cls.methods.get("__init__")
                    if ini is not None:
                        return Target(mod, cls, ini,
                                      "%s.__init__" % fn.attr)
                return None
            tname = ctx_mod.global_types.get(base)
            if tname:
                for cls in self.project.class_index.get(tname, ()):
                    meth = cls.methods.get(fn.attr)
                    if meth is not None:
                        return Target(cls.module, cls, meth,
                                      "%s.%s" % (tname, fn.attr))
            return None
        # self.attr.method(...) via __init__ type binding (the attr
        # may be bound by a BASE class's __init__ — merge hierarchy)
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Attribute) \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id == "self" and ctx_cls is not None:
            tname = self.project.class_attr_types(ctx_cls) \
                .get(fn.value.attr)
            if tname:
                for cls in self.project.class_index.get(tname, ()):
                    meth = cls.methods.get(fn.attr)
                    if meth is not None:
                        return Target(cls.module, cls, meth,
                                      "%s.%s" % (tname, fn.attr))
            return None
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in ctx_mod.functions:
                return Target(ctx_mod, None, ctx_mod.functions[name],
                              name)
            if name in ctx_mod.classes:
                cls = ctx_mod.classes[name]
                ini = cls.methods.get("__init__")
                if ini is not None:
                    return Target(ctx_mod, cls, ini,
                                  "%s.__init__" % name)
            target = ctx_mod.imports.get(name)
            if target and target[0] == "symbol":
                mod = self._module_for(target[1])
                if mod:
                    if target[2] in mod.functions:
                        return Target(mod, None,
                                      mod.functions[target[2]], name)
                    if target[2] in mod.classes:
                        cls = mod.classes[target[2]]
                        ini = cls.methods.get("__init__")
                        if ini is not None:
                            return Target(mod, cls, ini,
                                          "%s.__init__" % name)
        return None

    def iter_functions(self):
        """Every (module, cls_or_None, funcdef, label) definition in
        the project — the node set of the graph."""
        for mod in self.project.modules:
            for func in mod.functions.values():
                yield mod, None, func, func.name
            for cls in mod.classes.values():
                for mname, meth in cls.methods.items():
                    yield mod, cls, meth, "%s.%s" % (cls.name, mname)


# -- reactor-context enumeration ----------------------------------------

#: reactor scheduling API: the (position of the) callback argument
SCHEDULE_CALLS = {"call_soon": 0, "call_later": 1, "every": 1,
                  "post": 0}

#: conventional reactor callback method names. on_readable/on_writable
#: are excluded on purpose — they ARE the I/O layer (the one place
#: recv/send on the non-blocking socket is the job).
CALLBACK_METHODS = frozenset(("on_frame", "on_timer"))


def schedule_sites(mod):
    """[(call, enclosing ClassDef or None, enclosing function
    stack)] for every ``call_soon``/``call_later``/``every``/``post``
    call in the module, with scope tracked during the descent."""
    out = []

    def walk(node, cls_node, func_stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child, func_stack)
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                walk(child, cls_node, func_stack + [child])
                continue
            if isinstance(child, ast.Call) \
                    and call_name(child) in SCHEDULE_CALLS:
                out.append((child, cls_node, list(func_stack)))
            walk(child, cls_node, func_stack)

    walk(mod.tree, None, [])
    return out


def resolve_callable(cb, mod, cls_node, func_stack):
    """The FunctionDef/Lambda a callback REFERENCE names, resolved
    conservatively: a lambda inline, a Name through the enclosing
    function scopes then module functions, or a ``self.method`` on
    the enclosing class; -> (func, description) or (None, None)."""
    if isinstance(cb, ast.Lambda):
        return cb, "<lambda>"
    if isinstance(cb, ast.Name):
        for enclosing in reversed(func_stack):
            for sub in ast.walk(enclosing):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == cb.id:
                    return sub, cb.id
        fn = mod.functions.get(cb.id)
        if fn is not None:
            return fn, cb.id
        return None, None
    if isinstance(cb, ast.Attribute) \
            and isinstance(cb.value, ast.Name) \
            and cb.value.id == "self" and cls_node is not None:
        info = mod.classes.get(cls_node.name)
        if info is not None and cb.attr in info.methods:
            return (info.methods[cb.attr],
                    "%s.%s" % (cls_node.name, cb.attr))
    return None, None


def reactor_callbacks(project):
    """Every function that runs ON the reactor loop, with its class
    context: ``on_frame``/``on_timer`` methods and the resolvable
    targets of ``call_soon``/``call_later``/``every``/``post`` calls;
    -> [(mod, cls_node_or_None, func, where-description)]. The same
    function may appear more than once (a method that is also
    scheduled) — consumers dedupe findings, not contexts."""
    cached = getattr(project, "_reactor_callbacks_cache", None)
    if cached is not None:
        return cached
    out = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name in CALLBACK_METHODS:
                    out.append((mod, node, item,
                                "%s.%s" % (node.name, item.name)))
        for call, cls_node, func_stack in schedule_sites(mod):
            pos = SCHEDULE_CALLS[call_name(call)]
            if len(call.args) <= pos:
                continue
            target, desc = resolve_callable(
                call.args[pos], mod, cls_node, func_stack)
            if target is not None:
                out.append((mod, cls_node, target,
                            "%s (scheduled at line %d)"
                            % (desc, call.lineno)))
    # memoized per Project: three rule packs enumerate the same
    # loop contexts, and the project is immutable once built
    project._reactor_callbacks_cache = out
    return out


# -- exception hierarchy ------------------------------------------------

#: builtin exception -> direct base (enough of the stdlib tree for
#: coverage queries; anything unknown is assumed rooted at Exception)
_BUILTIN_BASES = {
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "BrokenPipeError": "ConnectionError",
    "TimeoutError": "OSError",
    "InterruptedError": "OSError",
    "BlockingIOError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "ChildProcessError": "OSError",
    "ProcessLookupError": "OSError",
    "IOError": "OSError",
    "KeyError": "LookupError",
    "IndexError": "LookupError",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "UnicodeError": "ValueError",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "IndentationError": "SyntaxError",
    "ModuleNotFoundError": "ImportError",
    "OSError": "Exception",
    "LookupError": "Exception",
    "ArithmeticError": "Exception",
    "ValueError": "Exception",
    "RuntimeError": "Exception",
    "SyntaxError": "Exception",
    "ImportError": "Exception",
    "TypeError": "Exception",
    "AttributeError": "Exception",
    "NameError": "Exception",
    "StopIteration": "Exception",
    "AssertionError": "Exception",
    "MemoryError": "Exception",
    "EOFError": "Exception",
    "BufferError": "Exception",
    "ReferenceError": "Exception",
    "Exception": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
}


def exception_ancestors(name, project):
    """The simple-name ancestor set of exception type ``name``
    (itself included): project classes walk their ``bases`` into the
    builtin table; unknown names conservatively root at Exception."""
    out = set()
    stack = [name]
    while stack:
        cur = stack.pop()
        if cur in out:
            continue
        out.add(cur)
        infos = project.class_index.get(cur, ())
        if infos:
            for info in infos:
                stack.extend(info.bases)
        elif cur in _BUILTIN_BASES:
            stack.append(_BUILTIN_BASES[cur])
        elif cur not in ("BaseException",):
            stack.append("Exception")
    return out


def exception_covered(raised, caught_names, project):
    """True when an exception of simple-name type ``raised`` is
    caught by a handler naming any of ``caught_names`` ("" = a bare
    ``except:``)."""
    if "" in caught_names or "BaseException" in caught_names:
        return True
    return bool(exception_ancestors(raised, project) & caught_names)


def handler_names(handler):
    """The simple type names one ``except`` clause catches ("" for a
    bare ``except:``; tuples are flattened)."""
    t = handler.type
    if t is None:
        return {""}
    out = set()
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        if isinstance(e, ast.Attribute):
            out.add(e.attr)
        elif isinstance(e, ast.Name):
            out.add(e.id)
    return out


# -- generic forward dataflow -------------------------------------------


class ForwardDataflow:
    """Generic forward-dataflow fixpoint over the call graph.

    Facts attach to (function, fact) states and flow caller→callee: a
    rule seeds entry states (:meth:`entries`), and for each state the
    rule's :meth:`transfer` walks the function body — recording any
    findings it likes — and yields ``(call_node, fact)`` pairs for
    the calls it wants followed. The driver resolves each call
    through the shared :class:`CallGraph` and enqueues the callee
    with the transferred fact; a (function, fact) pair is processed
    at most once, so the iteration reaches a fixpoint whenever facts
    are drawn from a finite lattice (frozensets of names, small
    tuples). Each state carries the diagnostic ``chain`` of labels
    that reached it.

    Subclass hooks:

    * ``entries()`` -> iterable of (mod, cls, func, fact, label)
    * ``transfer(mod, cls, func, fact, chain)`` -> iterable of
      (call_node, fact_for_callee)
    """

    def __init__(self, project):
        self.project = project
        self.graph = CallGraph(project)

    def entries(self):
        raise NotImplementedError

    def transfer(self, mod, cls, func, fact, chain):
        raise NotImplementedError

    def run(self):
        seen = set()
        work = []
        for mod, cls, func, fact, label in self.entries():
            key = (id(func), fact)
            if key not in seen:
                seen.add(key)
                work.append((mod, cls, func, fact, (label,)))
        while work:
            mod, cls, func, fact, chain = work.pop()
            if len(chain) > MAX_DEPTH:
                continue
            for call, out_fact in self.transfer(mod, cls, func, fact,
                                                chain):
                target = self.graph.resolve(mod, cls, call)
                if target is None:
                    continue
                key = (id(target.func), out_fact)
                if key in seen:
                    continue
                seen.add(key)
                work.append((target.module, target.cls, target.func,
                             out_fact, chain + (target.label,)))


# -- taint analysis -----------------------------------------------------

#: taint kinds — where an untrusted value originally entered
TAINT_WIRE = "wire"    # pickled master<->slave frame payloads
TAINT_HTTP = "http"    # HTTP bodies/headers/paths, fetched JSON
TAINT_ENV = "env"      # process environment overrides
_CONCRETE_KINDS = frozenset((TAINT_WIRE, TAINT_HTTP, TAINT_ENV))

#: handler methods whose parameters ARE the wire payload: the frame
#: dispatch entry points (transport HMAC authenticates the PEER, it
#: does not bound what the payload asks for)
WIRE_HANDLER_NAMES = frozenset((
    "handle", "on_frame", "apply_data_from_master",
    "apply_data_from_slave"))

#: attribute reads that are HTTP input wherever they appear
_HTTP_ATTRS = frozenset(("headers", "body"))
#: request-only attributes (too generic to taint on any receiver)
_HTTP_REQ_ATTRS = frozenset(("path", "query"))
_REQUESTISH = frozenset(("request", "req"))

#: unresolvable call names that read raw bytes off a socket
_RECV_NAMES = frozenset(("recv", "recv_into", "recvfrom",
                         "recv_frame", "recv_raw_frame"))

#: substrings that mark a call a sanitizer by naming convention —
#: the telemetry-hygiene ``*resolve*`` escape hatch, generalized
_SANITIZER_MARKERS = ("resolve", "sanitize", "clamp", "validate")

#: allocation-geometry sinks: first argument / shape keyword sizes
#: the allocation
_GEOMETRY_CALLS = frozenset(("zeros", "ones", "empty", "full",
                             "arange", "bytearray", "range"))
_GEOMETRY_KWARGS = frozenset(("shape", "size", "maxlen"))

#: keyword names that denote a filesystem/store target at any call
_PATH_KEYWORDS = frozenset(("path", "filename", "directory",
                            "dirname", "checkpoint", "store",
                            "refresh_store", "store_target"))
#: os.* names that are NOT path sinks
_PATH_SAFE = frozenset(("getenv", "environ", "getpid", "cpu_count",
                        "urandom", "fspath", "getcwd", "strerror",
                        "dup", "close", "read", "write", "pipe",
                        "fork", "kill", "waitpid", "sched_getaffinity"))


class TaintHit:
    """One tainted value reaching a sink, with its diagnostic chain."""

    __slots__ = ("module", "lineno", "sink", "kinds", "chain",
                 "detail")

    def __init__(self, module, lineno, sink, kinds, chain, detail):
        self.module = module    # Module the sink statement lives in
        self.lineno = lineno
        self.sink = sink        # "geometry"|"cardinality"|"path"|...
        self.kinds = kinds      # frozenset of TAINT_* kinds involved
        self.chain = chain      # label tuple from the entry function
        self.detail = detail    # human fragment naming the sink


def _annotated_sanitizer(mod, node):
    """True when a def/class carries ``# zlint: sanitizer`` on its
    own line, the line above, or a decorator line."""
    lines = mod.sanitizer_lines
    if node.lineno in lines or (node.lineno - 1) in lines:
        return True
    return any(d.lineno in lines
               for d in getattr(node, "decorator_list", ()))


def _sanitizer_named(name):
    low = (name or "").lower()
    return any(m in low for m in _SANITIZER_MARKERS)


def _bounded_container(mod_of_class, cls_name, project):
    """True when a container's constructor class is bounded: the
    class name says so (``Bounded*``/``*LRU*``) or the class def is
    annotated ``# zlint: sanitizer`` (the recipe for custom capped
    mappings)."""
    low = (cls_name or "").lower()
    if "bounded" in low or "lru" in low:
        return True
    for info in project.class_index.get(cls_name, ()):
        if _annotated_sanitizer(info.module, info.node):
            return True
    return False


def _guard_names(test):
    """Names a test bounds by comparison, membership, or isinstance —
    the 'explicit range/type guard' sanitizer: after the programmer
    compared a value against anything, both branches are treated as
    examined."""
    out = set()
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare):
            for operand in [sub.left] + list(sub.comparators):
                for n in ast.walk(operand):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(sub, ast.Call) \
                and call_name(sub) == "isinstance" and sub.args \
                and isinstance(sub.args[0], ast.Name):
            out.add(sub.args[0].id)
    return out


def _calls_compare_digest(node):
    """True when the subtree performs an HMAC verification."""
    return any(isinstance(sub, ast.Call)
               and call_name(sub) == "compare_digest"
               for sub in ast.walk(node))


def _param_names(func, skip_self):
    a = func.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if skip_self and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _map_call_args(call, target):
    """{callee param name: caller arg expr} for one resolved call
    (self offset applied for methods/constructors; *args stops the
    positional map)."""
    func = target.func
    pos = list(func.args.posonlyargs) + list(func.args.args)
    names = [p.arg for p in pos]
    if target.cls is not None and names and names[0] in ("self", "cls"):
        names = names[1:]
    out = {}
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(names):
            out[names[i]] = arg
    allowed = set(names) | {p.arg for p in func.args.kwonlyargs}
    for kw in call.keywords:
        if kw.arg and kw.arg in allowed:
            out[kw.arg] = kw.value
    return out


def _merge_env(env, a, b):
    for key in set(a) | set(b):
        tags = a.get(key, frozenset()) | b.get(key, frozenset())
        if tags:
            env[key] = tags
        else:
            env.pop(key, None)


class _TaintScan:
    """One intraprocedural pass: statement-ordered taint tracking
    with sink checks, guard/sanitizer kills, nested-def inlining and
    per-call interprocedural hand-off facts."""

    def __init__(self, eng, mod, cls, func, chain, summary_mode):
        self.eng = eng
        self.mod = mod
        self.cls = cls
        self.func = func
        self.chain = chain
        self.summary = summary_mode
        self.ret_tags = set()
        self.calls_out = []       # (call node, fact frozenset)
        self.hmac_ok = False
        self._nested = None       # lazy {name: FunctionDef}
        self._nested_active = set()

    # -- driving ---------------------------------------------------

    def run(self, env, hmac_ok):
        self.hmac_ok = hmac_ok
        self._suite(self.func.body, env)

    def _suite(self, stmts, env):
        for stmt in stmts:
            self._stmt(stmt, env)

    def _stmt(self, stmt, env):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                     # inlined at call sites instead
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, env)
            if _calls_compare_digest(stmt.test):
                self.hmac_ok = True
            for name in _guard_names(stmt.test):
                env.pop(name, None)
            body_env, else_env = dict(env), dict(env)
            self._suite(stmt.body, body_env)
            self._suite(stmt.orelse, else_env)
            _merge_env(env, body_env, else_env)
            return
        if isinstance(stmt, (ast.While,)):
            self._expr(stmt.test, env)
            for name in _guard_names(stmt.test):
                env.pop(name, None)
            self._loop_body(stmt.body, env)
            self._suite(stmt.orelse, env)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, env)
            self._bind(stmt.target, self._taint_of(stmt.iter, env),
                       env)
            self._loop_body(stmt.body, env)
            self._suite(stmt.orelse, env)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self._taint_of(item.context_expr, env),
                               env)
            self._suite(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self._suite(stmt.body, env)
            for handler in stmt.handlers:
                h_env = dict(env)
                self._suite(handler.body, h_env)
                _merge_env(env, env, h_env)
            self._suite(stmt.orelse, env)
            self._suite(stmt.finalbody, env)
            return
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, env)
            tags = self._taint_of(stmt.value, env)
            for tgt in stmt.targets:
                self._store(tgt, tags, env)
            if _calls_compare_digest(stmt.value):
                self.hmac_ok = True
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, env)
                self._store(stmt.target,
                            self._taint_of(stmt.value, env), env)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, env)
            tags = self._taint_of(stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                env[stmt.target.id] = \
                    env.get(stmt.target.id, frozenset()) | tags
            elif isinstance(stmt.target, ast.Subscript):
                self._growth(stmt.target, env)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value, env)
                if self.summary:
                    self.ret_tags |= self._taint_of(stmt.value, env)
            return
        for kind, child in iter_stmt_children(stmt):
            if kind == "expr":
                self._expr(child, env)
        if _calls_compare_digest(stmt):
            self.hmac_ok = True

    def _loop_body(self, body, env):
        # two passes so loop-carried taint (buf += chunk) reaches
        # uses textually above the assignment; sink dedup keeps the
        # second pass from double-reporting
        before = dict(env)
        self._suite(body, env)
        _merge_env(env, env, before)
        self._suite(body, env)

    def _bind(self, target, tags, env):
        if isinstance(target, ast.Name):
            if tags:
                env[target.id] = tags
            else:
                env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tags, env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tags, env)

    def _store(self, target, tags, env):
        if isinstance(target, ast.Subscript):
            self._growth(target, env)
            return
        self._bind(target, tags, env)

    # -- expression taint ------------------------------------------

    def _taint_of(self, expr, env):
        if expr is None or isinstance(expr, (ast.Constant, ast.Lambda)):
            return frozenset()
        if isinstance(expr, ast.Name):
            return env.get(expr.id, frozenset())
        if isinstance(expr, ast.Attribute):
            tags = set(self._taint_of(expr.value, env))
            if expr.attr in _HTTP_ATTRS:
                tags.add(TAINT_HTTP)
            elif expr.attr in _HTTP_REQ_ATTRS \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id in _REQUESTISH:
                tags.add(TAINT_HTTP)
            elif expr.attr == "environ":
                tags.add(TAINT_ENV)
            return frozenset(tags)
        if isinstance(expr, ast.Call):
            return self._call_taint(expr, env)
        if isinstance(expr, ast.Subscript):
            # value chosen BY a tainted key out of a trusted bounded
            # container is trusted; a tainted container's items are not
            return self._taint_of(expr.value, env)
        if isinstance(expr, ast.Compare):
            return frozenset()         # a bool is bounded
        if isinstance(expr, ast.IfExp):
            guarded = _guard_names(expr.test)
            inner = {k: v for k, v in env.items() if k not in guarded}
            return self._taint_of(expr.body, inner) \
                | self._taint_of(expr.orelse, inner)
        if isinstance(expr, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            env2 = self._comp_env(expr, env)
            if isinstance(expr, ast.DictComp):
                return self._taint_of(expr.key, env2) \
                    | self._taint_of(expr.value, env2)
            return self._taint_of(expr.elt, env2)
        out = set()
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                out |= self._taint_of(child, env)
        return frozenset(out)

    def _call_taint(self, call, env):
        name = call_name(call) or ""
        if _sanitizer_named(name):
            return frozenset()
        if name == "len":
            # a length is proportional to bytes the transport already
            # capped — not attacker amplification
            return frozenset()
        if name == "min" and len(call.args) >= 2:
            arg_tags = [self._taint_of(a, env) for a in call.args]
            if any(not t for t in arg_tags):
                return frozenset()     # clamped by an untainted bound
        if name == "getenv" or attr_chain(call.func) in (
                "os.environ.get",):
            return frozenset((TAINT_ENV,))
        if name == "urlopen":
            return frozenset((TAINT_HTTP,))
        if name in _RECV_NAMES:
            return frozenset((TAINT_WIRE,))
        target = self.eng.graph.resolve(self.mod, self.cls, call)
        if target is not None:
            if _sanitizer_named(target.label) or _annotated_sanitizer(
                    target.module, target.func):
                return frozenset()
            ret_kinds, ret_params = self.eng.summary_for(target.func)
            tags = set(ret_kinds)
            argmap = _map_call_args(call, target)
            for pname in ret_params:
                if pname in argmap:
                    tags |= self._taint_of(argmap[pname], env)
            return frozenset(tags)
        if name == "get" and isinstance(call.func, ast.Attribute):
            # bounded-lookup shape: dict.get(tainted_key) returns a
            # value from the RECEIVER's universe
            return self._taint_of(call.func.value, env)
        out = set()
        if isinstance(call.func, ast.Attribute):
            out |= self._taint_of(call.func.value, env)
        for arg in call.args:
            out |= self._taint_of(arg, env)
        for kw in call.keywords:
            out |= self._taint_of(kw.value, env)
        return frozenset(out)

    def _comp_env(self, comp, env):
        env2 = dict(env)
        for gen in comp.generators:
            self._bind(gen.target, self._taint_of(gen.iter, env2),
                       env2)
            for cond in gen.ifs:
                for nm in _guard_names(cond):
                    env2.pop(nm, None)
        return env2

    # -- sink + propagation walk -----------------------------------

    def _expr(self, expr, env):
        if expr is None or not isinstance(expr, ast.expr) \
                or isinstance(expr, ast.Lambda):
            return
        if isinstance(expr, ast.Call):
            self._check_call(expr, env)
            self._expr(expr.func, env)
            for arg in expr.args:
                self._expr(arg, env)
            for kw in expr.keywords:
                self._expr(kw.value, env)
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            env2 = self._comp_env(expr, env)
            for gen in expr.generators:
                self._expr(gen.iter, env)
                for cond in gen.ifs:
                    self._expr(cond, env2)
            if isinstance(expr, ast.DictComp):
                self._expr(expr.key, env2)
                self._expr(expr.value, env2)
            else:
                self._expr(expr.elt, env2)
            return
        if isinstance(expr, ast.BinOp) \
                and isinstance(expr.op, ast.Mult):
            self._check_mult(expr, env)
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self._expr(child, env)

    def _check_mult(self, binop, env):
        for side, other in ((binop.left, binop.right),
                            (binop.right, binop.left)):
            literal = isinstance(other, (ast.List, ast.Tuple)) or (
                isinstance(other, ast.Constant)
                and isinstance(other.value, (str, bytes)))
            if not literal:
                continue
            kinds = self._taint_of(side, env) \
                & frozenset((TAINT_WIRE, TAINT_HTTP))
            if kinds:
                self._hit(binop, "geometry", kinds,
                          "sequence repetition count")

    def _check_call(self, call, env):
        name = call_name(call) or ""
        target = self.eng.graph.resolve(self.mod, self.cls, call)
        if _sanitizer_named(name) or (target is not None and (
                _sanitizer_named(target.label)
                or _annotated_sanitizer(target.module, target.func))):
            # handing a tainted value TO a sanitizer is the
            # sanctioned pattern, never a sink — and taint does not
            # cross into it
            return
        wire_http = frozenset((TAINT_WIRE, TAINT_HTTP))
        if name in _GEOMETRY_CALLS:
            sized = list(call.args[:1]) + [
                kw.value for kw in call.keywords
                if kw.arg in _GEOMETRY_KWARGS]
            if name == "range":
                sized = list(call.args)
            for arg in sized:
                kinds = self._taint_of(arg, env) & wire_http
                if kinds:
                    self._hit(call, "geometry", kinds,
                              "%s(...) extent" % name)
                    break
        chain = attr_chain(call.func) or ""
        root = chain.split(".")[0] if chain else ""
        if (name == "open" and isinstance(call.func, ast.Name)) or (
                root in ("os", "shutil", "glob")
                and name not in _PATH_SAFE):
            for arg in call.args:
                kinds = self._taint_of(arg, env) & wire_http
                if kinds:
                    self._hit(call, "path", kinds,
                              "%s(...) filesystem argument"
                              % (chain or name))
                    break
        for kw in call.keywords:
            if kw.arg in _PATH_KEYWORDS:
                kinds = self._taint_of(kw.value, env) & wire_http
                if kinds:
                    self._hit(call, "path", kinds,
                              "%s=... store/filesystem target"
                              % kw.arg)
        if name in ("loads", "load") and root in ("pickle", "marshal") \
                and not self.hmac_ok and call.args:
            kinds = self._taint_of(call.args[0], env) \
                & _CONCRETE_KINDS
            if kinds:
                self._hit(call, "deserialize", kinds,
                          "%s.%s(...) of unverified input"
                          % (root, name))
        if name in ("setdefault", "add") \
                and isinstance(call.func, ast.Attribute) \
                and call.args:
            kinds = self._taint_of(call.args[0], env) \
                & _CONCRETE_KINDS
            if kinds:
                self._container_growth(call, call.func.value, kinds,
                                       env)
        self._propagate(call, env, target)

    def _growth(self, subscript, env):
        kinds = self._taint_of(subscript.slice, env) & _CONCRETE_KINDS
        if kinds:
            self._container_growth(subscript, subscript.value, kinds,
                                   env)

    def _container_growth(self, node, container, kinds, env):
        """Persistent container keyed by a tainted value: self-attr
        and module-global containers only — a function-local dict
        dies with the call and cannot accumulate."""
        project = self.eng.project
        if isinstance(container, ast.Attribute) \
                and isinstance(container.value, ast.Name) \
                and container.value.id == "self" \
                and self.cls is not None:
            cname = project.class_attr_types(self.cls) \
                .get(container.attr)
            if cname and _bounded_container(self.mod, cname, project):
                return
            self._hit(node, "cardinality", kinds,
                      "self.%s keyed by untrusted value"
                      % container.attr)
            return
        if isinstance(container, ast.Name) \
                and container.id in self.eng.module_globals(self.mod):
            cname = self.mod.global_types.get(container.id)
            if cname and _bounded_container(self.mod, cname, project):
                return
            self._hit(node, "cardinality", kinds,
                      "module-global %s keyed by untrusted value"
                      % container.id)

    def _propagate(self, call, env, target):
        if target is None:
            fn = call.func
            if isinstance(fn, ast.Name):
                if self._nested is None:
                    self._nested = nested_functions(self.func)
                nested = self._nested.get(fn.id)
                if nested is not None \
                        and id(nested) not in self._nested_active:
                    self._inline_nested(nested, call, env)
            return
        if self.summary:
            return
        argmap = _map_call_args(call, target)
        fact = set()
        for pname, argexpr in argmap.items():
            for kind in self._taint_of(argexpr, env) \
                    & _CONCRETE_KINDS:
                fact.add("%s:%s" % (kind, pname))
        if not fact:
            return
        if self.hmac_ok:
            fact.add("<verified>")
        self.calls_out.append((call, frozenset(fact)))

    def _inline_nested(self, nested, call, env):
        """Scan a closure defined in this function with the caller's
        env — CallGraph cannot see nested defs, but loadgen-style
        recursive allocators live there."""
        env2 = dict(env)
        names = _param_names(nested, skip_self=False)
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(names):
                env2[names[i]] = self._taint_of(arg, env)
        for kw in call.keywords:
            if kw.arg:
                env2[kw.arg] = self._taint_of(kw.value, env)
        self._nested_active.add(id(nested))
        try:
            self._suite(nested.body, env2)
        finally:
            self._nested_active.discard(id(nested))

    def _hit(self, node, sink, kinds, detail):
        if self.summary:
            return
        self.eng.record(self.mod, node.lineno, sink, kinds,
                        self.chain, detail)


class _TaintFlow(ForwardDataflow):
    """The interprocedural driver: every function seeds with an empty
    fact (local source -> local sink), wire handlers seed with all
    parameters wire-tainted; facts are frozensets of ``kind:param``
    strings plus an optional ``<verified>`` HMAC marker."""

    def __init__(self, eng):
        ForwardDataflow.__init__(self, eng.project)
        self.eng = eng
        self.graph = eng.graph

    def entries(self):
        for mod, cls, func, label in self.eng.functions:
            yield mod, cls, func, frozenset(), label
            if func.name in WIRE_HANDLER_NAMES:
                fact = frozenset(
                    "%s:%s" % (TAINT_WIRE, p)
                    for p in _param_names(func,
                                          skip_self=cls is not None))
                if fact:
                    yield mod, cls, func, fact, label

    def transfer(self, mod, cls, func, fact, chain):
        env = {}
        hmac_ok = False
        for entry in fact:
            if entry == "<verified>":
                hmac_ok = True
                continue
            kind, _, pname = entry.partition(":")
            env[pname] = env.get(pname, frozenset()) | {kind}
        scan = _TaintScan(self.eng, mod, cls, func, chain,
                          summary_mode=False)
        scan.run(env, hmac_ok)
        return scan.calls_out


class TaintEngine:
    """Whole-program taint analysis over a Project.

    Sources: wire handler parameters and recv results, HTTP
    headers/bodies/paths and fetched JSON, ``os.environ`` reads.
    Sanitizers: ``*resolve*``/``*clamp*``/``*validate*``-named calls,
    ``# zlint: sanitizer``-annotated defs, explicit comparison/
    isinstance/membership guards, ``min()`` against an untainted
    bound, and ``hmac.compare_digest`` domination (deserialize only).
    Sinks: allocation geometry, persistent-container growth keyed by
    tainted values, filesystem/store targets, unverified
    ``pickle.loads``. Results are :class:`TaintHit` records the
    ``rules_taint`` pack turns into findings."""

    _SUMMARY_ROUNDS = 5

    def __init__(self, project):
        self.project = project
        self.graph = CallGraph(project)
        self.functions = list(self._iter_functions())
        self.hits = []
        self._hit_keys = set()
        self._summaries = {}
        self._globals = {}
        self._classinfo = {id(cls.node): cls
                           for mod in project.modules
                           for cls in mod.classes.values()}

    def _iter_functions(self):
        for mod in self.project.modules:
            for func in mod.functions.values():
                yield mod, None, func, func.name
            for cls in mod.classes.values():
                for mname, meth in cls.methods.items():
                    yield mod, cls, meth, "%s.%s" % (cls.name, mname)

    def module_globals(self, mod):
        names = self._globals.get(id(mod))
        if names is None:
            names = set(mod.global_types)
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
            self._globals[id(mod)] = names
        return names

    def summary_for(self, func):
        return self._summaries.get(id(func),
                                   (frozenset(), frozenset()))

    def record(self, mod, lineno, sink, kinds, chain, detail):
        key = (mod.relpath, lineno, sink)
        if key in self._hit_keys:
            return
        self._hit_keys.add(key)
        self.hits.append(TaintHit(mod, lineno, sink, kinds, chain,
                                  detail))

    def _compute_summaries(self):
        """Per-function return summaries (source kinds + parameter
        pass-through) to a cross-call fixpoint, so ``recv_frame``'s
        result and a JSON-fetch helper's result taint their callers."""
        for _ in range(self._SUMMARY_ROUNDS):
            changed = False
            for mod, cls, func, label in self.functions:
                env = {}
                for p in _param_names(func, skip_self=cls is not None):
                    env[p] = frozenset(("param:%s" % p,))
                scan = _TaintScan(self, mod, cls, func, (label,),
                                  summary_mode=True)
                scan.run(env, hmac_ok=False)
                kinds = frozenset(t for t in scan.ret_tags
                                  if not t.startswith("param:"))
                params = frozenset(t[6:] for t in scan.ret_tags
                                   if t.startswith("param:"))
                new = (kinds & _CONCRETE_KINDS, params)
                if self._summaries.get(id(func)) != new:
                    self._summaries[id(func)] = new
                    changed = True
            if not changed:
                break

    def run(self):
        self._compute_summaries()
        _TaintFlow(self).run()
        self.hits.sort(key=lambda h: (h.module.relpath, h.lineno,
                                      h.sink))
        return self.hits


def taint_hits(project):
    """Memoized whole-program taint pass — the four taint rules share
    one engine run exactly like the reactor rules share
    :func:`reactor_callbacks`."""
    cached = getattr(project, "_taint_hits_cache", None)
    if cached is None:
        cached = TaintEngine(project).run()
        project._taint_hits_cache = cached
    return cached


# -- graph utilities ----------------------------------------------------


def tarjan_sccs(edges):
    """Strongly connected components with more than one node, over an
    edge set/dict keyed ``(a, b)`` — the minimal cycle witness the
    lock-order rule reports."""
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index, low, on, stack = {}, {}, set(), []
    sccs, counter = [], [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph[v]:
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)
    for v in list(graph):
        if v not in index:
            strongconnect(v)
    return sccs
