"""The shared whole-program analysis engine under every rule pack.

Before this module, each rule pack grew its own call resolution —
``rules_threads`` carried ~130 lines of ``self.method``/module-alias/
symbol-import resolution, ``rules_reactor`` its own callback-target
resolver, ``rules_purity`` a third copy specialized to ops helpers —
and none of them could see ACROSS functions in a principled way. This
module factors all of that into one place:

* **name helpers** — :func:`call_name`, :func:`attr_chain`,
  :func:`receiver_name`, :func:`assigned_name`,
  :func:`canonical_import_prefixes`: the small AST spellings every
  rule needs;
* **statement traversal** — :func:`iter_stmt_children` /
  :func:`walk_statements`: child iteration that descends the
  structural carriers (``ExceptHandler``, ``match_case``) whose
  bodies are exactly where retry/error paths live, so no rule grows a
  blind spot there again;
* **:class:`CallGraph`** — interprocedural call resolution over a
  :class:`~veles.analysis.core.Project`: direct calls,
  ``self.method`` (hierarchy-merged), ``self.attr.method`` through
  ``__init__`` type bindings, module-alias and symbol-import calls,
  constructor calls, and module-level instance methods. One resolver,
  one behavior, every rule;
* **reactor-context enumeration** — :func:`reactor_callbacks` /
  :func:`schedule_sites` / :func:`resolve_callable`: the shared
  answer to "which functions run ON the loop" (``on_frame``/
  ``on_timer`` methods plus ``call_soon``/``call_later``/``every``/
  ``post`` targets), used by ``reactor-purity``,
  ``profiler-safety`` and ``loop-exception-safety`` alike;
* **:class:`ForwardDataflow`** — a generic forward fixpoint over the
  call graph: facts seed at entry functions and flow caller→callee
  through a rule-supplied transfer function until no new
  (function, fact) state appears. ``loop-exception-safety`` runs on
  it with caught-exception sets as the lattice;
* **graph utilities** — :func:`tarjan_sccs` (the lock-order cycle
  detector), exception-hierarchy queries (:func:`exception_covered`)
  shared by the dataflow rules.

Everything here is pure AST work over the already-parsed project —
the engine never re-reads a file.
"""

import ast

#: bound on interprocedural walk depth — cycles are caught by the
#: per-walk visited sets, this only caps pathological chains
MAX_DEPTH = 40

# -- name helpers -------------------------------------------------------


def call_name(node):
    """The rightmost simple name a call invokes (``a.b.f()`` -> 'f',
    ``f()`` -> 'f'), or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def attr_chain(expr):
    """Dotted name of an attribute chain (``a.b.c`` -> 'a.b.c'), or
    None when the chain does not root in a plain Name."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def receiver_name(node):
    """The rightmost name of a call receiver: ``a.b.profiler`` ->
    'profiler', ``profiler`` -> 'profiler', else ''."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return receiver_name(node.func)
    return ""


def target_key(t):
    """A comparable key for an assignment target: ``x`` -> "x",
    ``self.x`` -> "self.x", else None."""
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
        return "%s.%s" % (t.value.id, t.attr)
    return None


def assigned_name(mod, call):
    """The Name/self-attribute a constructor call is assigned to, as
    a comparable key ("x" or "self.x"), or None for a bare call."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and node.value is call:
            return target_key(node.targets[0])
    return None


def canonical_import_prefixes(mod):
    """local name -> canonical dotted path, resolving every import
    style (``import numpy as np``, ``from numpy import random``,
    ``from time import monotonic``) so namespace bans cannot be
    dodged by how a module was imported."""
    out = {}
    for local, target in mod.imports.items():
        if target[0] == "module":
            dotted = target[1]
            if "." in dotted and local == dotted.split(".")[0]:
                # plain ``import numpy.random`` binds the TOP package
                # name; the attribute chain spells out the rest
                dotted = local
        else:
            dotted = "%s.%s" % (target[1], target[2])
        out[local] = dotted
    return out


# -- statement traversal ------------------------------------------------


def iter_stmt_children(node):
    """Yield ``("stmt", s)`` / ``("expr", e)`` for the children of a
    statement, descending structural nodes that are neither stmt nor
    expr but CARRY statements (``ExceptHandler``, ``match_case``) —
    their bodies are exactly where retry/error paths live, so
    skipping them silently weakens every rule built on this."""
    for field in ast.iter_child_nodes(node):
        if isinstance(field, ast.stmt):
            yield "stmt", field
        elif isinstance(field, ast.expr):
            yield "expr", field
        else:
            for sub in ast.iter_child_nodes(field):
                if isinstance(sub, ast.stmt):
                    yield "stmt", sub
                elif isinstance(sub, ast.expr):
                    yield "expr", sub


def walk_statements(func):
    """Every statement in ``func``'s body, in source order, WITHOUT
    descending into nested function/class definitions (they execute
    later, not here)."""
    out = []

    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            out.append(stmt)
            for kind, child in iter_stmt_children(stmt):
                if kind == "stmt":
                    walk([child])
    walk(func.body)
    return out


def scoped_nodes(node):
    """Every node under ``node`` that executes in ITS scope — nested
    function/lambda/class subtrees are skipped (they run later,
    elsewhere). The shared spelling of the walk a half-dozen rules
    used to hand-roll."""
    out = []

    def walk(cur):
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda,
                                  ast.ClassDef)):
                continue
            out.append(child)
            walk(child)
    walk(node)
    return out


def iter_calls(node):
    """Call nodes at or under ``node`` that execute in its scope
    (nested def/lambda bodies excluded — a deferred closure's calls
    run on whatever thread runs IT, not here)."""
    out = [node] if isinstance(node, ast.Call) else []
    out.extend(n for n in scoped_nodes(node)
               if isinstance(n, ast.Call))
    return out


def novel_calls(mod, func, seen, classify):
    """Yield ``(call, label)`` for each call in ``func`` that
    ``classify`` recognizes and that has not been reported yet —
    the shared dedup shell of every scan-a-callback rule. ``seen``
    is keyed (relpath, lineno, label) across contexts, so a method
    that is both a conventional callback and a scheduled target is
    reported once."""
    for sub in ast.walk(func):
        if not isinstance(sub, ast.Call):
            continue
        label = classify(sub)
        if label is None:
            continue
        key = (mod.relpath, sub.lineno, label)
        if key in seen:
            continue
        seen.add(key)
        yield sub, label


def test_mentions(test, markers):
    """True when an if-test contains a string constant carrying any
    of ``markers`` — the branch-detection convention route rules key
    on (``==``, ``startswith``, tuple membership: any spelling)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Constant) \
                and isinstance(sub.value, str) \
                and any(m in sub.value for m in markers):
            return True
    return False


def nested_functions(func):
    """{name: FunctionDef} of the function/async defs nested anywhere
    inside ``func`` (excluding ``func`` itself)."""
    out = {}
    for node in ast.walk(func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not func:
            out[node.name] = node
    return out


# -- the interprocedural call graph -------------------------------------


class Target:
    """One resolved callee: where it lives and what to call it in a
    diagnostic chain."""

    __slots__ = ("module", "cls", "func", "label")

    def __init__(self, module, cls, func, label):
        self.module = module    # Module the definition lives in
        self.cls = cls          # ClassInfo or None
        self.func = func        # FunctionDef / AsyncFunctionDef
        self.label = label      # "Class.meth" / "alias.func" / name


class CallGraph:
    """Interprocedural call resolution over a Project.

    One resolver for every rule pack: ``self.method(...)`` through
    the hierarchy-merged method table, ``self.attr.method(...)``
    through ``__init__`` type bindings (base-class bindings
    included), module-alias calls (``telemetry.counter(...)``),
    symbol imports (``from x import f``; also the ``from veles
    import telemetry`` module-through-symbol form), module-level
    functions, constructor calls (resolved to ``__init__``) and
    methods on module-level typed instances. Unresolvable calls
    return None — every rule on this graph is conservative by
    construction."""

    def __init__(self, project):
        self.project = project

    def _module_for(self, dotted):
        return self.project.module_by_dotted(dotted)

    def resolve(self, ctx_mod, ctx_cls, call):
        """-> :class:`Target` or None for one ``ast.Call``."""
        fn = call.func
        # self.method(...)
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name):
            base = fn.value.id
            if base == "self" and ctx_cls is not None:
                cls, meth = self.project.find_method(ctx_cls, fn.attr)
                if meth is not None:
                    return Target(cls.module, cls, meth,
                                  "%s.%s" % (cls.name, fn.attr))
                return None
            # module_alias.func(...) / global_instance.method(...)
            target = ctx_mod.imports.get(base)
            if target and target[0] == "symbol":
                # ``from veles import telemetry`` imports a MODULE
                # through the symbol form — resolve it as one
                if self._module_for("%s.%s" % (target[1], target[2])):
                    target = ("module",
                              "%s.%s" % (target[1], target[2]))
            if target and target[0] == "module":
                mod = self._module_for(target[1])
                if mod and fn.attr in mod.functions:
                    return Target(mod, None, mod.functions[fn.attr],
                                  "%s.%s" % (base, fn.attr))
                if mod and fn.attr in mod.classes:
                    cls = mod.classes[fn.attr]
                    ini = cls.methods.get("__init__")
                    if ini is not None:
                        return Target(mod, cls, ini,
                                      "%s.__init__" % fn.attr)
                return None
            tname = ctx_mod.global_types.get(base)
            if tname:
                for cls in self.project.class_index.get(tname, ()):
                    meth = cls.methods.get(fn.attr)
                    if meth is not None:
                        return Target(cls.module, cls, meth,
                                      "%s.%s" % (tname, fn.attr))
            return None
        # self.attr.method(...) via __init__ type binding (the attr
        # may be bound by a BASE class's __init__ — merge hierarchy)
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Attribute) \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id == "self" and ctx_cls is not None:
            tname = self.project.class_attr_types(ctx_cls) \
                .get(fn.value.attr)
            if tname:
                for cls in self.project.class_index.get(tname, ()):
                    meth = cls.methods.get(fn.attr)
                    if meth is not None:
                        return Target(cls.module, cls, meth,
                                      "%s.%s" % (tname, fn.attr))
            return None
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in ctx_mod.functions:
                return Target(ctx_mod, None, ctx_mod.functions[name],
                              name)
            if name in ctx_mod.classes:
                cls = ctx_mod.classes[name]
                ini = cls.methods.get("__init__")
                if ini is not None:
                    return Target(ctx_mod, cls, ini,
                                  "%s.__init__" % name)
            target = ctx_mod.imports.get(name)
            if target and target[0] == "symbol":
                mod = self._module_for(target[1])
                if mod:
                    if target[2] in mod.functions:
                        return Target(mod, None,
                                      mod.functions[target[2]], name)
                    if target[2] in mod.classes:
                        cls = mod.classes[target[2]]
                        ini = cls.methods.get("__init__")
                        if ini is not None:
                            return Target(mod, cls, ini,
                                          "%s.__init__" % name)
        return None

    def iter_functions(self):
        """Every (module, cls_or_None, funcdef, label) definition in
        the project — the node set of the graph."""
        for mod in self.project.modules:
            for func in mod.functions.values():
                yield mod, None, func, func.name
            for cls in mod.classes.values():
                for mname, meth in cls.methods.items():
                    yield mod, cls, meth, "%s.%s" % (cls.name, mname)


# -- reactor-context enumeration ----------------------------------------

#: reactor scheduling API: the (position of the) callback argument
SCHEDULE_CALLS = {"call_soon": 0, "call_later": 1, "every": 1,
                  "post": 0}

#: conventional reactor callback method names. on_readable/on_writable
#: are excluded on purpose — they ARE the I/O layer (the one place
#: recv/send on the non-blocking socket is the job).
CALLBACK_METHODS = frozenset(("on_frame", "on_timer"))


def schedule_sites(mod):
    """[(call, enclosing ClassDef or None, enclosing function
    stack)] for every ``call_soon``/``call_later``/``every``/``post``
    call in the module, with scope tracked during the descent."""
    out = []

    def walk(node, cls_node, func_stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child, func_stack)
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                walk(child, cls_node, func_stack + [child])
                continue
            if isinstance(child, ast.Call) \
                    and call_name(child) in SCHEDULE_CALLS:
                out.append((child, cls_node, list(func_stack)))
            walk(child, cls_node, func_stack)

    walk(mod.tree, None, [])
    return out


def resolve_callable(cb, mod, cls_node, func_stack):
    """The FunctionDef/Lambda a callback REFERENCE names, resolved
    conservatively: a lambda inline, a Name through the enclosing
    function scopes then module functions, or a ``self.method`` on
    the enclosing class; -> (func, description) or (None, None)."""
    if isinstance(cb, ast.Lambda):
        return cb, "<lambda>"
    if isinstance(cb, ast.Name):
        for enclosing in reversed(func_stack):
            for sub in ast.walk(enclosing):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == cb.id:
                    return sub, cb.id
        fn = mod.functions.get(cb.id)
        if fn is not None:
            return fn, cb.id
        return None, None
    if isinstance(cb, ast.Attribute) \
            and isinstance(cb.value, ast.Name) \
            and cb.value.id == "self" and cls_node is not None:
        info = mod.classes.get(cls_node.name)
        if info is not None and cb.attr in info.methods:
            return (info.methods[cb.attr],
                    "%s.%s" % (cls_node.name, cb.attr))
    return None, None


def reactor_callbacks(project):
    """Every function that runs ON the reactor loop, with its class
    context: ``on_frame``/``on_timer`` methods and the resolvable
    targets of ``call_soon``/``call_later``/``every``/``post`` calls;
    -> [(mod, cls_node_or_None, func, where-description)]. The same
    function may appear more than once (a method that is also
    scheduled) — consumers dedupe findings, not contexts."""
    cached = getattr(project, "_reactor_callbacks_cache", None)
    if cached is not None:
        return cached
    out = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name in CALLBACK_METHODS:
                    out.append((mod, node, item,
                                "%s.%s" % (node.name, item.name)))
        for call, cls_node, func_stack in schedule_sites(mod):
            pos = SCHEDULE_CALLS[call_name(call)]
            if len(call.args) <= pos:
                continue
            target, desc = resolve_callable(
                call.args[pos], mod, cls_node, func_stack)
            if target is not None:
                out.append((mod, cls_node, target,
                            "%s (scheduled at line %d)"
                            % (desc, call.lineno)))
    # memoized per Project: three rule packs enumerate the same
    # loop contexts, and the project is immutable once built
    project._reactor_callbacks_cache = out
    return out


# -- exception hierarchy ------------------------------------------------

#: builtin exception -> direct base (enough of the stdlib tree for
#: coverage queries; anything unknown is assumed rooted at Exception)
_BUILTIN_BASES = {
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "BrokenPipeError": "ConnectionError",
    "TimeoutError": "OSError",
    "InterruptedError": "OSError",
    "BlockingIOError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "ChildProcessError": "OSError",
    "ProcessLookupError": "OSError",
    "IOError": "OSError",
    "KeyError": "LookupError",
    "IndexError": "LookupError",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "UnicodeError": "ValueError",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "IndentationError": "SyntaxError",
    "ModuleNotFoundError": "ImportError",
    "OSError": "Exception",
    "LookupError": "Exception",
    "ArithmeticError": "Exception",
    "ValueError": "Exception",
    "RuntimeError": "Exception",
    "SyntaxError": "Exception",
    "ImportError": "Exception",
    "TypeError": "Exception",
    "AttributeError": "Exception",
    "NameError": "Exception",
    "StopIteration": "Exception",
    "AssertionError": "Exception",
    "MemoryError": "Exception",
    "EOFError": "Exception",
    "BufferError": "Exception",
    "ReferenceError": "Exception",
    "Exception": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
}


def exception_ancestors(name, project):
    """The simple-name ancestor set of exception type ``name``
    (itself included): project classes walk their ``bases`` into the
    builtin table; unknown names conservatively root at Exception."""
    out = set()
    stack = [name]
    while stack:
        cur = stack.pop()
        if cur in out:
            continue
        out.add(cur)
        infos = project.class_index.get(cur, ())
        if infos:
            for info in infos:
                stack.extend(info.bases)
        elif cur in _BUILTIN_BASES:
            stack.append(_BUILTIN_BASES[cur])
        elif cur not in ("BaseException",):
            stack.append("Exception")
    return out


def exception_covered(raised, caught_names, project):
    """True when an exception of simple-name type ``raised`` is
    caught by a handler naming any of ``caught_names`` ("" = a bare
    ``except:``)."""
    if "" in caught_names or "BaseException" in caught_names:
        return True
    return bool(exception_ancestors(raised, project) & caught_names)


def handler_names(handler):
    """The simple type names one ``except`` clause catches ("" for a
    bare ``except:``; tuples are flattened)."""
    t = handler.type
    if t is None:
        return {""}
    out = set()
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        if isinstance(e, ast.Attribute):
            out.add(e.attr)
        elif isinstance(e, ast.Name):
            out.add(e.id)
    return out


# -- generic forward dataflow -------------------------------------------


class ForwardDataflow:
    """Generic forward-dataflow fixpoint over the call graph.

    Facts attach to (function, fact) states and flow caller→callee: a
    rule seeds entry states (:meth:`entries`), and for each state the
    rule's :meth:`transfer` walks the function body — recording any
    findings it likes — and yields ``(call_node, fact)`` pairs for
    the calls it wants followed. The driver resolves each call
    through the shared :class:`CallGraph` and enqueues the callee
    with the transferred fact; a (function, fact) pair is processed
    at most once, so the iteration reaches a fixpoint whenever facts
    are drawn from a finite lattice (frozensets of names, small
    tuples). Each state carries the diagnostic ``chain`` of labels
    that reached it.

    Subclass hooks:

    * ``entries()`` -> iterable of (mod, cls, func, fact, label)
    * ``transfer(mod, cls, func, fact, chain)`` -> iterable of
      (call_node, fact_for_callee)
    """

    def __init__(self, project):
        self.project = project
        self.graph = CallGraph(project)

    def entries(self):
        raise NotImplementedError

    def transfer(self, mod, cls, func, fact, chain):
        raise NotImplementedError

    def run(self):
        seen = set()
        work = []
        for mod, cls, func, fact, label in self.entries():
            key = (id(func), fact)
            if key not in seen:
                seen.add(key)
                work.append((mod, cls, func, fact, (label,)))
        while work:
            mod, cls, func, fact, chain = work.pop()
            if len(chain) > MAX_DEPTH:
                continue
            for call, out_fact in self.transfer(mod, cls, func, fact,
                                                chain):
                target = self.graph.resolve(mod, cls, call)
                if target is None:
                    continue
                key = (id(target.func), out_fact)
                if key in seen:
                    continue
                seen.add(key)
                work.append((target.module, target.cls, target.func,
                             out_fact, chain + (target.label,)))


# -- graph utilities ----------------------------------------------------


def tarjan_sccs(edges):
    """Strongly connected components with more than one node, over an
    edge set/dict keyed ``(a, b)`` — the minimal cycle witness the
    lock-order rule reports."""
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index, low, on, stack = {}, {}, set(), []
    sccs, counter = [], [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph[v]:
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)
    for v in list(graph):
        if v not in index:
            strongconnect(v)
    return sccs
