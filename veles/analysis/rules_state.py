"""checkpoint-state: units with mutable run-state must be resumable.

The durability layer (``Workflow.checkpoint_state`` →
``unit.get_state()`` per unit; PR 4) silently drops any unit that
forgot to implement the protocol: the checkpoint writes fine, the
resume "works", and the unit restarts from its constructor defaults —
epoch counters reset, rollback history gone, save limits re-armed.
This rule closes that hole statically: every ``Unit`` subclass whose
``run()`` (directly or through ``self.*`` helpers) assigns instance
attributes must either implement ``get_state``/``checkpoint_state``
(its own or inherited) or carry a pragma stating why its state is
ephemeral::

    class EndPoint(TrivialUnit):   # zlint: disable=checkpoint-state
        ...
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

_STATE_METHODS = ("get_state", "checkpoint_state")


def _run_mutations(project, graph, cls):
    """Attributes ``run()`` assigns on self, following ``self.*``
    helper calls through the shared call graph (bounded depth)."""
    run = cls.methods.get("run")
    if run is None:
        return []
    writes = []
    seen = set()

    def scan(mod, owner, func, depth):
        if id(func) in seen or depth > 8:
            return
        seen.add(id(func))
        for node in ast.walk(func):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        writes.append((t.attr, node.lineno))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                # only self.helper() calls: another object's method
                # writes ITS state, not this unit's
                target = graph.resolve(mod, owner, node)
                if target is not None \
                        and target.func.name not in (
                            "run", "initialize", "stop"):
                    scan(target.module, target.cls, target.func,
                         depth + 1)

    scan(cls.module, cls, run, 0)
    return writes


@register("checkpoint-state", "error",
          "Unit subclasses whose run() mutates instance state must "
          "implement get_state/checkpoint_state",
          scope="module")
def check_checkpoint_state(project):
    findings = []
    graph = engine.CallGraph(project)
    for mod in project.modules:
        for cls in mod.classes.values():
            if not project.is_subclass_of(cls, "Unit"):
                continue
            if "run" not in cls.methods:
                continue           # inherited run: the definer owns it
            writes = _run_mutations(project, graph, cls)
            if not writes:
                continue
            has_state = any(
                project.find_method(cls, m)[1] is not None
                for m in _STATE_METHODS)
            if has_state:
                continue
            attrs = sorted({a for a, _ in writes})
            findings.append(Finding(
                mod.relpath, cls.node.lineno, "checkpoint-state",
                "error",
                "%s.run() mutates %s but the unit implements no "
                "get_state/checkpoint_state — this state silently "
                "resets on resume" % (cls.name, ", ".join(
                    "self.%s" % a for a in attrs[:4])
                    + (", ..." if len(attrs) > 4 else "")),
                "implement get_state()/set_state() covering the "
                "mutated attributes, or pragma the class with the "
                "reason the state is ephemeral"))
    return findings
