"""tracer-purity: the jit-traced step closures must stay pure.

StepCompiler traces every unit's ``xla_run(ctx)`` under ``jax.jit``
(``veles/accelerated_units.py``): the closure runs ONCE at trace time
and whatever it does outside the tensor algebra is baked into (or
silently dropped from) the compiled program. Inside the traced scope —
``xla_run`` methods in ``veles/znicz_tpu/ops/`` plus everything they
reach through ``self.*`` and same-module helper calls — this rule
bans:

* ``numpy.random.*`` — host randomness freezes at trace time; use
  ``jax.random`` with ``ctx.fold_key(self)``;
* ``time.*`` — trace-time wall clock constant-folds into the program;
* ``print(...)`` — executes once at trace time, never per step (use
  ``jax.debug.print`` if needed);
* ``.item()`` / ``float()`` / ``int()`` on a value read from the
  tracing context — concretizing a tracer either crashes or silently
  hardcodes the first batch's value;
* assigning ``self.*`` — trace-time mutation runs once, not per step,
  and hides state from the checkpoint protocol.

``float()/int()`` are only flagged when their argument is (derived
from) a ``ctx.get(...)`` / ``ctx.unit_params(...)`` read — shape
arithmetic like ``int(numpy.prod(shape))`` over static python ints is
legitimate and common.
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

#: method names that enter jax tracing (StepCompiler collects these)
_TRACED_METHODS = ("xla_run",)

#: path fragment selecting the traced-op modules
_OPS_FRAGMENT = "znicz_tpu/ops"


def _in_ops(mod):
    return _OPS_FRAGMENT in mod.relpath.replace("\\", "/")


#: (canonical dotted prefix, why it is banned, fix hint)
_BANNED_PREFIXES = (
    ("numpy.random",
     "host randomness freezes at trace time",
     "use jax.random with ctx.fold_key(self) for per-step "
     "randomness"),
    ("time",
     "the trace-time clock constant-folds into the compiled program",
     "time the dispatch outside the jitted function"),
)


def _banned(chain, prefixes):
    """(why, hint) when ``chain`` canonicalizes into a banned
    namespace, else None."""
    parts = chain.split(".")
    root = prefixes.get(parts[0])
    if root is None:
        return None
    canonical = ".".join([root] + parts[1:])
    for prefix, why, hint in _BANNED_PREFIXES:
        if canonical == prefix or canonical.startswith(prefix + "."):
            return why, hint
    return None


def _ctx_tainted_names(func):
    """Local names holding traced tensors: assigned from a
    ``ctx.get(...)``/``ctx.unit_params(...)`` read, or DERIVED from an
    already-tainted name (``s = t * 2``) — propagated to a fixpoint so
    ``float(s)`` is caught as surely as ``float(ctx.get("x"))``."""
    tainted = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                if not _expr_touches(node.value, tainted):
                    continue
                targets = []
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        targets.append(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        targets.extend(el.id for el in t.elts
                                       if isinstance(el, ast.Name))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and _expr_touches(node.value, tainted):
                targets = [node.target.id]
            else:
                continue
            for name in targets:
                if name not in tainted:
                    tainted.add(name)
                    changed = True
    return tainted


def _expr_touches(expr, tainted):
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) \
                and (sub.id in tainted or sub.id == "ctx"):
            return True
        chain = engine.attr_chain(sub) \
            if isinstance(sub, ast.Attribute) else None
        if chain and (chain == "ctx" or chain.startswith("ctx.")):
            return True
    return False


def _scan_traced(mod, cls_name, func, findings, seen_funcs,
                 project, graph, depth=0):
    if id(func) in seen_funcs or depth > 20:
        return
    seen_funcs.add(id(func))
    prefixes = engine.canonical_import_prefixes(mod)
    tainted = _ctx_tainted_names(func)
    where = "%s.%s" % (cls_name, func.name) if cls_name else func.name

    for node in ast.walk(func):
        # self mutation
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    findings.append(Finding(
                        mod.relpath, node.lineno, "tracer-purity",
                        "error",
                        "%s mutates self.%s inside the traced scope "
                        "— runs once at trace time, not per step"
                        % (where, t.attr),
                        "return the value through ctx.set(...) or "
                        "move the mutation to run()/initialize()"))
        if not isinstance(node, ast.Call):
            continue
        # .item() on anything (incl. chained calls like x.sum().item())
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            findings.append(Finding(
                mod.relpath, node.lineno, "tracer-purity", "error",
                "%s calls .item() inside the traced scope — "
                "concretizing a tracer fails under jit" % where,
                "keep the value symbolic; reduce with jnp and let "
                "the step return it"))
            continue
        chain = engine.attr_chain(node.func) \
            if isinstance(node.func, ast.Attribute) else None
        # numpy.random.* / time.* under ANY import spelling
        if chain:
            ban = _banned(chain, prefixes)
            if ban is not None:
                why, hint = ban
                findings.append(Finding(
                    mod.relpath, node.lineno, "tracer-purity",
                    "error",
                    "%s calls %s inside the traced scope — %s"
                    % (where, chain, why), hint))
        elif isinstance(node.func, ast.Name):
            fname = node.func.id
            ban = _banned(fname, prefixes)
            if ban is not None:
                why, hint = ban
                findings.append(Finding(
                    mod.relpath, node.lineno, "tracer-purity",
                    "error",
                    "%s calls %s inside the traced scope — %s"
                    % (where, fname, why), hint))
            elif fname == "print":
                findings.append(Finding(
                    mod.relpath, node.lineno, "tracer-purity",
                    "error",
                    "%s calls print() inside the traced scope — it "
                    "runs once at trace time, never per step" % where,
                    "drop it, or use jax.debug.print for runtime "
                    "prints"))
            elif fname in ("float", "int") and node.args \
                    and _expr_touches(node.args[0], tainted):
                findings.append(Finding(
                    mod.relpath, node.lineno, "tracer-purity",
                    "error",
                    "%s calls %s() on a traced value inside the "
                    "traced scope — concretizing a tracer fails "
                    "under jit" % (where, fname),
                    "keep the value symbolic (jnp ops) or read it "
                    "host-side after the step"))
        # follow helper calls through the shared call graph —
        # self.m(...), same-module functions, module-alias calls
        # (``A.relu(x)``, the dominant style in ops/), symbol imports
        # and constructors — staying inside the traced-op modules
        cls = mod.classes.get(cls_name) if cls_name else None
        target = graph.resolve(mod, cls, node)
        # constructors stay unfollowed: trace-time attribute setup on
        # a FRESH object is not persistent-state mutation
        if target is not None and _in_ops(target.module) \
                and target.func.name != "__init__":
            _scan_traced(target.module,
                         target.cls.name if target.cls else None,
                         target.func, findings, seen_funcs, project,
                         graph, depth + 1)


@register("tracer-purity", "error",
          "jit-traced step closures must not do host I/O, host "
          "randomness, tracer concretization or self mutation",
          scope="module")
def check_tracer_purity(project):
    findings = []
    # ONE project-wide seen set: a shared helper (conv_math etc.) is
    # scanned once, not re-reported per calling module
    seen = set()
    graph = engine.CallGraph(project)
    for mod in project.modules:
        if not _in_ops(mod):
            continue
        for cls in mod.classes.values():
            for mname in _TRACED_METHODS:
                meth = cls.methods.get(mname)
                if meth is not None:
                    _scan_traced(mod, cls.name, meth, findings, seen,
                                 project, graph)
    return findings
