"""telemetry-hygiene: keep the metrics registry cheap and bounded.

Two failure modes the telemetry core cannot defend against at
runtime:

* **families created inside loops** — ``telemetry.counter(...)`` is
  idempotent-by-name but pays the registry lock + dict lookup every
  call; a creation inside a ``for``/``while`` body is either a hot
  path that should hold a :class:`veles.telemetry.LazyChild`, or an
  unbounded family leak when the name is formatted per iteration;
* **label values minted from identities** — ``.labels(id(x))``,
  ``uuid4()``, ``token_hex()``, ``getpid()`` or a ``*_id`` loop
  variable create a new child per value; Prometheus series are
  forever, so identity-labelled series grow without bound (the
  cluster aggregation path deliberately bounds its ``slave`` label
  via per-token TTL eviction — see ``MasterServer._tele_states``);
* **span names minted from identities** — the tracing twin of the
  label failure mode: ``tracer.span("job-%s" % job_id)`` turns every
  request into its own timeline row (Perfetto groups by name) and an
  unbounded name universe in any aggregating backend. The identity
  belongs in the span's ``args`` (``span("job.serve",
  job_id=job_id)``), where it is per-event payload, not cardinality.
* **label values taken from the wire** (ISSUE 18) — a ``.labels(...)``
  argument that reads ``request.headers``/``request.body`` hands the
  INTERNET the keys of your series dict: every novel header value is
  a new child that lives forever. Caller attribution must pass
  through a bounded resolver first (``TenantTable.resolve`` maps
  unknown keys to one ``other`` bucket — veles/serving/tenants.py);
  a ``*resolve*``-named call wrapping the whole argument is the
  recognized escape hatch.
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

_FACTORIES = ("counter", "gauge", "histogram")

#: calls whose result is an unbounded identity when used as a label
_IDENTITY_CALLS = ("id", "uuid4", "uuid1", "token_hex", "token_urlsafe",
                   "getpid", "get_ident", "monotonic", "time",
                   "perf_counter")


def _is_factory_call(node, telemetry_aliases, registry_handles):
    """True for ``telemetry.counter(...)`` / ``registry.counter(...)``
    shaped calls carrying a metric-name first argument — literal OR
    computed: a name formatted per iteration is the worse failure
    mode (one leaked family per value), so it must not be exempt."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _FACTORIES:
        return False
    if not node.args:
        return False
    base = fn.value
    if isinstance(base, ast.Name) and (
            base.id in telemetry_aliases
            or base.id in registry_handles):
        return True
    # <anything>.get_registry().counter(...) or a var named *registry*
    if isinstance(base, ast.Call) and isinstance(
            base.func, ast.Attribute) \
            and base.func.attr == "get_registry":
        return True
    if isinstance(base, ast.Name) and "registry" in base.id.lower():
        return True
    return False


def _telemetry_aliases(mod):
    """Local names the telemetry module is imported under, through
    any import spelling (the shared canonicalization)."""
    return {local for local, dotted
            in engine.canonical_import_prefixes(mod).items()
            if dotted == "veles.telemetry"}


def _registry_handles(mod):
    """Local names bound from a ``*.get_registry()`` call — the
    handle style the runtime actually uses (``reg =
    telemetry.get_registry()``), whatever the variable is called."""
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr == "get_registry":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _loop_spans(tree):
    """[(start, end)] line spans of for/while bodies."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            end = getattr(node, "end_lineno", node.lineno)
            spans.append((node.lineno, end))
    return spans


def _has_identity(node):
    """True when the expression involves an identity-shaped value: a
    call to an id/uuid/token factory, or a name ending in
    ``_id``/named ``uuid``/``token``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            fname = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if fname in _IDENTITY_CALLS:
                return True
        elif isinstance(sub, (ast.Name, ast.Attribute)):
            n = (sub.id if isinstance(sub, ast.Name)
                 else sub.attr).lower()
            if n.endswith("_id") or n in ("uuid", "token"):
                return True
    return False


def _identity_labelled(node):
    """True when a ``.labels(...)`` call passes an identity-shaped
    value."""
    return any(_has_identity(arg) for arg in
               list(node.args) + [kw.value for kw in node.keywords])


#: attribute names that read caller-controlled bytes off the wire
_WIRE_SOURCES = ("headers", "body")


def _resolver_wrapped(node):
    """True when the whole expression is a call to a ``*resolve*``
    function — the bounded escape hatch (``table.resolve(...)``,
    ``tenants.resolve(...)``, ``_resolve_tenant(...)``)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    fname = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return "resolve" in fname.lower()


def _wire_derived(node):
    """True when the expression reads ``*.headers``/``*.body``
    anywhere inside — ``request.headers.get("x-veles-tenant")``,
    ``req.headers["x-api-key"]``, ``request.body`` — i.e. the value
    universe is whatever callers choose to send."""
    if _resolver_wrapped(node):
        return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _WIRE_SOURCES:
            return True
    return False


def _wire_labelled(node):
    """True when a ``.labels(...)`` call passes a header/body-derived
    value without the resolver escape."""
    return any(_wire_derived(arg) for arg in
               list(node.args) + [kw.value for kw in node.keywords])


def _is_span_call(node):
    """``*.span(name, ...)`` / ``*.add_complete(name, ...)`` calls on
    a telemetry/tracer-shaped receiver — ``telemetry.span(...)``,
    ``tracer.span(...)``, ``telemetry.tracer.add_complete(...)``, a
    ``self._tracer``-style attribute. Receiver-shape matching keeps
    unrelated ``.span`` methods out."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) \
            or fn.attr not in ("span", "add_complete") \
            or not node.args:
        return False
    base = fn.value
    if isinstance(base, ast.Name):
        name = base.id.lower()
        return name == "telemetry" or "tracer" in name
    if isinstance(base, ast.Attribute):
        return "tracer" in base.attr.lower()
    return False


def _formatted_identity(node):
    """True when a string-building expression (``%``, f-string,
    ``.format``, ``+``) interpolates an identity-shaped value."""
    operands = []
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        operands.append(node.right)
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        operands.extend((node.left, node.right))
    elif isinstance(node, ast.JoinedStr):
        operands.extend(v.value for v in node.values
                        if isinstance(v, ast.FormattedValue))
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "format":
        operands.extend(node.args)
        operands.extend(kw.value for kw in node.keywords)
    return any(_has_identity(op) for op in operands)


@register("telemetry-hygiene", "error",
          "no instrument creation in loops; no unbounded identity "
          "label values or span names", scope="module")
def check_telemetry_hygiene(project):
    findings = []
    for mod in project.modules:
        aliases = _telemetry_aliases(mod)
        handles = _registry_handles(mod)
        spans = None
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_factory_call(node, aliases, handles):
                if spans is None:
                    spans = _loop_spans(mod.tree)
                if any(s <= node.lineno <= e for s, e in spans):
                    findings.append(Finding(
                        mod.relpath, node.lineno, "telemetry-hygiene",
                        "error",
                        "instrument family created inside a loop — "
                        "pays the registry lock per iteration (or "
                        "leaks families if the name varies)",
                        "hoist the creation out of the loop or hold "
                        "a telemetry.LazyChild at the call site"))
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "labels" \
                    and (node.args or node.keywords):
                if _identity_labelled(node):
                    findings.append(Finding(
                        mod.relpath, node.lineno, "telemetry-hygiene",
                        "error",
                        "label value minted from an identity (id/uuid/"
                        "token/pid) — every value is a new series that "
                        "lives forever",
                        "label by a bounded dimension (kind, model, "
                        "unit name); aggregate identities before "
                        "labelling or bound them with TTL eviction"))
                if _wire_labelled(node):
                    findings.append(Finding(
                        mod.relpath, node.lineno, "telemetry-hygiene",
                        "error",
                        "label value read from request headers/body "
                        "without a bounded resolver — callers mint "
                        "series at will (unbounded cardinality from "
                        "the wire)",
                        "pass the raw value through a bounded "
                        "resolver first, e.g. "
                        "tenants.TenantTable.resolve(...), which "
                        "folds unknown keys into one 'other' bucket"))
            if _is_span_call(node) \
                    and _formatted_identity(node.args[0]):
                findings.append(Finding(
                    mod.relpath, node.lineno, "telemetry-hygiene",
                    "error",
                    "span name minted from a per-request identity "
                    "(id/uuid/token/pid) — every request becomes its "
                    "own timeline row / unbounded name cardinality "
                    "(same failure mode as identity label values)",
                    "use a constant span name and carry the identity "
                    "in the span args: span(\"job.serve\", "
                    "job_id=job_id)"))
    return findings
