"""Generic hygiene rules mirroring the ruff baseline (pyflakes F401 /
F841 / pycodestyle E722) so the gate enforces them even where ruff is
not installed — one config surface (``pyproject.toml [tool.ruff]``),
two enforcers, same verdicts.

* ``unused-import`` — module-level imports never referenced anywhere
  in the file. Function-level imports are exempt (availability probes
  like ``import jax  # noqa`` and lazy heavy imports are idiomatic
  here); so are ``__init__.py`` re-export surfaces and names escaped
  with ``# noqa``.
* ``unused-variable`` — a local bound by a simple ``name = expr``
  assignment and never read afterwards anywhere in the function.
  Underscore-prefixed names, tuple unpacks, augmented targets and
  functions that call ``locals()``/``eval``/``exec`` are exempt
  (matching pyflakes F841's conservatism).
* ``bare-except`` — ``except:`` catches ``SystemExit`` and
  ``KeyboardInterrupt``, turning Ctrl-C into an infinite loop in any
  retry path. Name the exceptions (``except Exception:`` at the
  broadest).
"""

import ast
import os
import re

from veles.analysis import engine
from veles.analysis.core import Finding, register

_NOQA_RE = re.compile(r"#\s*noqa\b", re.IGNORECASE)


@register("bare-except", "error",
          "except: swallows KeyboardInterrupt/SystemExit",
          scope="module")
def check_bare_except(project):
    findings = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                findings.append(Finding(
                    mod.relpath, node.lineno, "bare-except", "error",
                    "bare except: catches SystemExit and "
                    "KeyboardInterrupt — Ctrl-C and sys.exit() die "
                    "here",
                    "catch Exception (or the specific errors) "
                    "instead"))
    return findings


_DYNAMIC_SCOPE = ("locals", "vars", "eval", "exec")


@register("unused-variable", "warning",
          "locals assigned by simple statements and never read",
          scope="module")
def check_unused_variable(project):
    findings = []
    for mod in project.modules:
        funcs = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append(node)
        for func in funcs:
            # anything that can read names dynamically defeats the
            # analysis — skip the whole function (pyflakes does too)
            if any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Name)
                   and n.func.id in _DYNAMIC_SCOPE
                   for n in ast.walk(func)):
                continue
            assigns = {}           # name -> first-assign lineno
            # nested scopes are scanned on their own (shared walk)
            for node in engine.scoped_nodes(func):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name) \
                            and not t.id.startswith("_"):
                        assigns.setdefault(t.id, node.lineno)
            if not assigns:
                continue
            read = set()
            for node in ast.walk(func):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, (ast.Load, ast.Del)):
                    read.add(node.id)
                elif isinstance(node, ast.AugAssign) \
                        and isinstance(node.target, ast.Name):
                    read.add(node.target.id)
                elif isinstance(node, (ast.Global, ast.Nonlocal)):
                    read.update(node.names)
            # names nested functions close over count as read
            for node in ast.walk(func):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not func:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name):
                            read.add(sub.id)
            for name, lineno in sorted(assigns.items()):
                if name in read:
                    continue
                findings.append(Finding(
                    mod.relpath, lineno, "unused-variable", "warning",
                    "local %r is assigned but never read" % name,
                    "drop the binding (keep the right-hand side if "
                    "it has side effects), or name it _%s" % name))
    return findings


@register("unused-import", "warning",
          "dead module-level imports", scope="module")
def check_unused_import(project):
    findings = []
    for mod in project.modules:
        if os.path.basename(mod.path) == "__init__.py":
            continue               # re-export surface
        lines = mod.source.splitlines()
        imported = {}              # local name -> (lineno, display)
        for node in mod.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    imported[local] = (node.lineno, a.name)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    imported[a.asname or a.name] = (
                        node.lineno, "%s.%s" % (node.module or "",
                                                a.name))
        if not imported:
            continue
        used = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        # names listed in __all__ count as used (export surface)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "__all__"
                            for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        used.add(sub.value)
        for name, (lineno, display) in sorted(imported.items()):
            if name in used:
                continue
            if lineno <= len(lines) and _NOQA_RE.search(
                    lines[lineno - 1]):
                continue
            findings.append(Finding(
                mod.relpath, lineno, "unused-import", "warning",
                "%r imported but unused" % display,
                "delete the import (or mark an intentional "
                "re-export with `# noqa: F401`)"))
    return findings
