"""resource-leak: every acquisition needs a release on every path.

The runtime is built from acquire/release pairs — sockets
(``socket.create_connection``), serving registries and decode
batchers (``.close()``), KV slot grants (``pool.grant()`` /
``pool.release(slot)``), background masters
(``start_background()`` / ``request_stop()``) — and the failure mode
that actually bites is never the happy path: it is the EXCEPTION
path, where a constructor or helper between the acquire and the
``try/finally`` raises and the resource outlives the function (the
PR-7 bench ``MasterServer`` leak: slaves built between
``start_background()`` and the ``finally`` meant one failed build
leaked the master's serving thread and listener for the rest of the
process).

The rule is function-local and deliberately conservative. It tracks
a resource from its acquisition site when the handle is a plain
local name (``sock = socket.create_connection(...)``) or a bare
discarded call (``pool.grant()`` with no assignment — a slot nobody
can ever release). Acquisitions stored straight into attributes,
containers or ``with`` items are owned elsewhere and skipped.
Recognized acquisitions:

* module functions: ``socket.socket``, ``socket.create_connection``,
  ``socket.create_server``, ``open`` (outside ``with``);
* methods: ``.grant()`` (KV slot pools — released by
  ``.release(slot)``), ``.start_background()`` (the handle is the
  receiver; released by ``request_stop``/``shutdown``/``kill``);
* constructors with a close contract: ``ModelRegistry``,
  ``ContinuousBatcher``.

From the acquisition forward, events on the handle are classified as
**release** (``.close()``/``.shutdown()``/``.stop()``/
``.request_stop()``/``.kill()``/``.server_close()`` on the handle,
or the handle passed to a ``.release(...)`` call), **escape**
(returned/yielded, stored into an attribute/subscript/container,
aliased, handed to a CapWord constructor or an
``append``/``add``/``put``/``register``-shaped call — ownership
moved, this function is off the hook), or **risky** (any other call
that can raise; calls ON the handle itself and benign
logging/builtin calls are exempt). Findings:

* **never released** — no release and no escape anywhere after the
  acquisition;
* **leaked on the exception path** — a risky call sits between the
  acquisition and the first release/escape WITHOUT a ``try`` whose
  ``finally``/``except`` releases the handle: if that call raises,
  the resource leaks.

Deliberate gaps (documented, not bugs): ``.accept()``'d sockets (the
reactor owns their lifecycle), handles whose risky window consists
only of calls on the handle itself (``sock.bind`` raising leaks an
fd — tolerated for brevity), and cross-function ownership transfer
through plain argument passing (borrowing a handle is not owning
it).
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

#: canonical dotted module functions that acquire (via the shared
#: import canonicalization, so aliasing cannot dodge them)
_ACQUIRE_FUNCS = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "socket.create_server": "listening socket",
}

#: methods whose RESULT is the resource handle (``slot = pool.grant()``)
_RESULT_METHODS = {
    "grant": "KV slot",
}

#: methods that turn their RECEIVER into the resource handle
#: (``server.start_background()`` — release via request_stop on the
#: receiver, whatever the call returned)
_RECEIVER_METHODS = {
    "start_background": "background server",
}

#: CapWord constructors with a close contract in this tree
_ACQUIRE_CTORS = {
    "ModelRegistry": "model registry",
    "ContinuousBatcher": "decode batcher",
}

_RELEASE_VERBS = frozenset((
    "close", "shutdown", "stop", "request_stop", "kill",
    "server_close", "release", "disconnect", "terminate"))

#: call names that take ownership of an argument (container adds,
#: registrations)
_ESCAPE_VERBS = frozenset(("append", "add", "put", "insert",
                           "register", "setdefault", "track"))

#: calls that cannot meaningfully fail mid-window (logging, trivial
#: builtins, clock reads)
_BENIGN_CALLS = frozenset((
    "len", "isinstance", "int", "float", "str", "repr", "bool",
    "min", "max", "round", "getattr", "hasattr", "print", "format",
    "debug", "info", "warning", "error", "exception", "log",
    "perf_counter", "monotonic", "time", "range", "sorted", "list",
    "dict", "tuple", "set"))


def _root_name(expr):
    """The base Name of an attribute/call chain, or None."""
    while isinstance(expr, (ast.Attribute, ast.Subscript, ast.Call)):
        expr = expr.value if not isinstance(expr, ast.Call) \
            else expr.func
    return expr.id if isinstance(expr, ast.Name) else None


def _acquisition(stmt, prefixes):
    """(handle_name_or_None, call, what) when ``stmt`` acquires a
    trackable resource, else None. handle None = a bare discarded
    acquisition (leak by construction)."""
    call = None
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.value, ast.Call):
        call = stmt.value
    elif isinstance(stmt, ast.Expr) \
            and isinstance(stmt.value, ast.Call):
        call = stmt.value
    if call is None:
        return None
    name = engine.call_name(call)
    if name in _RECEIVER_METHODS \
            and isinstance(call.func, ast.Attribute):
        # the handle is the RECEIVER: server.start_background() is
        # released by server.request_stop(), whatever it returned
        if isinstance(call.func.value, ast.Name):
            return (call.func.value.id, call,
                    _RECEIVER_METHODS[name])
        return None       # self.X.start_background(): owned elsewhere
    what = _classify(call, prefixes)
    if what is None:
        return None
    if isinstance(stmt, ast.Expr):
        return None, call, what        # discarded handle
    target = stmt.targets[0]
    if isinstance(target, ast.Name):
        return target.id, call, what
    return None           # attribute/subscript store: owned elsewhere


def _classify(call, prefixes):
    """What resource a call acquires through its RESULT, or None."""
    name = engine.call_name(call)
    if name == "open" and isinstance(call.func, ast.Name):
        return "file handle"
    if name in _ACQUIRE_CTORS:
        return _ACQUIRE_CTORS[name]
    if name in _RESULT_METHODS \
            and isinstance(call.func, ast.Attribute):
        return _RESULT_METHODS[name]
    chain = engine.attr_chain(call.func)
    if chain:
        parts = chain.split(".")
        root = prefixes.get(parts[0], parts[0])
        canonical = ".".join([root] + parts[1:])
        if canonical in _ACQUIRE_FUNCS:
            return _ACQUIRE_FUNCS[canonical]
    return None


def _linear_statements(func):
    """[(stmt, try_stack, handler_tries, branches)] in source order,
    skipping nested defs; try_stack is the chain of enclosing
    ``ast.Try`` nodes whose BODY contains the statement,
    handler_tries the set of tries in whose ``except`` handlers it
    lives (a handler of the try that performed an acquisition runs
    on a path where the resource may never have existed), and
    branches maps each enclosing ``ast.If`` to the arm ("body"/
    "orelse") the statement sits in — sibling arms are mutually
    exclusive, so an acquisition in one arm is never live in the
    other."""
    out = []

    def walk(stmts, stack, handlers, branches):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            out.append((stmt, list(stack), set(handlers),
                        dict(branches)))
            if isinstance(stmt, ast.Try):
                walk(stmt.body, stack + [stmt], handlers, branches)
                for h in stmt.handlers:
                    walk(h.body, stack, handlers | {id(stmt)},
                         branches)
                walk(stmt.orelse, stack, handlers, branches)
                walk(stmt.finalbody, stack, handlers, branches)
                continue
            if isinstance(stmt, ast.If):
                walk(stmt.body, stack, handlers,
                     {**branches, id(stmt): "body"})
                walk(stmt.orelse, stack, handlers,
                     {**branches, id(stmt): "orelse"})
                continue
            for kind, child in engine.iter_stmt_children(stmt):
                if kind == "stmt":
                    walk([child], stack, handlers, branches)
    walk(func.body, [], set(), {})
    return out


def _releases_handle(stmts, handle):
    """True when a statement list (a finally/except body) releases
    ``handle``."""
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and _is_release(node, handle):
                return True
    return False


def _is_release(call, handle):
    name = engine.call_name(call)
    if name not in _RELEASE_VERBS:
        return False
    if isinstance(call.func, ast.Attribute) \
            and _root_name(call.func.value) == handle:
        return True
    # pool.release(slot): the handle rides as an argument
    return any(isinstance(a, ast.Name) and a.id == handle
               for a in call.args)


def _is_escape(node, handle):
    """True when ``node`` (a statement or expression) transfers
    ownership of ``handle`` out of this function."""
    if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
        # only the HANDLE itself (or a container shipping it) is an
        # ownership transfer; `return sock.getpeername()[0]` returns
        # a derived value and still owes the close
        value = node.value
        if value is None:
            return False
        if isinstance(value, ast.Name) and value.id == handle:
            return True
        if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            return any(isinstance(e, ast.Name) and e.id == handle
                       for e in value.elts)
        return False
    if isinstance(node, ast.Assign):
        used = any(isinstance(s, ast.Name) and s.id == handle
                   for s in ast.walk(node.value))
        if not used:
            return False
        bare = isinstance(node.value, ast.Name) \
            and node.value.id == handle
        for t in node.targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                return True          # stored: owned elsewhere now
            if bare and isinstance(t, ast.Name) and t.id != handle:
                return True          # plain alias: `other = handle`
            if isinstance(t, (ast.Tuple, ast.List)):
                # only a STORE-shaped element makes this an escape;
                # `a, b = f(handle), g()` is a use, not a transfer
                if any(isinstance(e, (ast.Attribute, ast.Subscript))
                       for e in t.elts):
                    return True
        return False
    if isinstance(node, ast.Call):
        if not any(isinstance(a, ast.Name) and a.id == handle
                   for a in list(node.args)
                   + [kw.value for kw in node.keywords]):
            return False
        name = engine.call_name(node)
        if name and (name[:1].isupper() or name in _ESCAPE_VERBS):
            return True              # constructor / container add
    return False


def _calls_in(stmt):
    """Call nodes lexically in one statement's own expressions,
    nested defs/lambdas excluded (the shared scoped walk)."""
    out = []
    for kind, child in engine.iter_stmt_children(stmt):
        if kind == "expr":
            out.extend(engine.iter_calls(child))
    return out


def _scan_function(mod, func, prefixes, findings):
    ordered = _linear_statements(func)
    for idx, (stmt, acq_stack, _h, acq_branches) in enumerate(ordered):
        got = _acquisition(stmt, prefixes)
        if got is None:
            continue
        handle, call, what = got
        acq_tries = {id(t) for t in acq_stack}
        # `with` items and `return socket.socket()` are not leaks
        if isinstance(stmt, ast.Return):
            continue
        if handle is None:
            findings.append(Finding(
                mod.relpath, call.lineno, "resource-leak", "error",
                "%s acquired and immediately discarded — nothing "
                "can ever release it" % what,
                "bind the handle and release it (or drop the call "
                "if the resource is not needed)"))
            continue
        first_safe = None          # (order idx, stmt)
        risky = []                 # [(lineno, name, try_stack)]
        for jdx in range(idx + 1, len(ordered)):
            nstmt, nstack, nhandlers, nbranches = ordered[jdx]
            if nhandlers & acq_tries:
                # a handler of the try the acquisition sits in: on
                # this path the acquisition may never have happened
                continue
            if any(nbranches.get(k) not in (None, arm)
                   for k, arm in acq_branches.items()):
                # the sibling arm of a conditional the acquisition
                # sits in: mutually exclusive, never the same path
                continue
            # a re-acquisition into the same name restarts tracking
            regot = _acquisition(nstmt, prefixes)
            if regot is not None and regot[0] == handle:
                break
            if _is_escape(nstmt, handle):
                first_safe = jdx
                break
            hit_safe = False
            for ncall in _calls_in(nstmt):
                if _is_release(ncall, handle) \
                        or _is_escape(ncall, handle):
                    hit_safe = True
                    break
                name = engine.call_name(ncall)
                if name in _BENIGN_CALLS:
                    continue
                root = _root_name(ncall.func)
                if root == handle:
                    continue       # calls on the handle itself
                risky.append((ncall.lineno, name or "?", nstack))
            if hit_safe:
                first_safe = jdx
                break
        if first_safe is None:
            findings.append(Finding(
                mod.relpath, call.lineno, "resource-leak", "error",
                "%s %r acquired here is never released on any path "
                "out of %s()" % (what, handle, func.name),
                "release it in a finally (or `with "
                "contextlib.closing(...)`), or return/store the "
                "handle so an owner can"))
            continue
        unprotected = [
            (line, name) for line, name, stack in risky
            if not any(
                _releases_handle(t.finalbody, handle)
                or any(_releases_handle(h.body, handle)
                       for h in t.handlers)
                for t in stack)]
        if unprotected:
            line, name = unprotected[0]
            findings.append(Finding(
                mod.relpath, call.lineno, "resource-leak", "error",
                "%s %r leaks if %s() at line %d raises — the "
                "release does not happen until line %d and no "
                "try/finally covers the gap"
                % (what, handle, name, line,
                   ordered[first_safe][0].lineno),
                "move the acquisition-to-release span into "
                "try/finally (acquire; try: ...; finally: "
                "release), or release in an except before "
                "re-raising"))
    return findings


@register("resource-leak", "error",
          "acquired resources (sockets, registries, KV slots, "
          "background servers) must be released on every path, "
          "exception edges included", scope="module")
def check_resource_leak(project):
    findings = []
    for mod in project.modules:
        prefixes = engine.canonical_import_prefixes(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                _scan_function(mod, node, prefixes, findings)
    return findings
