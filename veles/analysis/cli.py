"""``velescli lint`` — run zlint over files/directories.

Exit codes follow the gate contract: **0** clean, **1** findings,
**2** usage error (bad path, unknown rule). ``--format json`` (alias
``--json``) emits the findings as a JSON array sorted by (file, line,
rule) with repo-relative paths; ``--format sarif`` emits a SARIF
2.1.0 log for CI annotation surfaces and editors. Both are
byte-stable for identical inputs — CI can diff them. ``--changed-only
[REF]`` lints only files changed vs a git ref (default HEAD, plus
untracked files) for fast pre-commit runs, falling back to the full
tree with a warning when git is unavailable.
"""

import argparse
import json
import os
import subprocess
import sys

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _default_paths():
    """With no paths given, lint the installed veles package."""
    import veles
    return [os.path.dirname(os.path.abspath(veles.__file__))]


class BadRefError(ValueError):
    """--changed-only named a ref git cannot resolve. A distinct type
    so a typo'd ref is a LOUD usage error (exit 2), never a silent
    full-tree fallback behind a misleading warning."""


def _changed_files(ref):
    """Absolute paths of .py files changed vs ``ref`` (tracked diff +
    untracked), or None when git cannot answer (no git binary, not a
    repository — the caller falls back to the full tree). A bad
    ``ref`` in a working repository raises :class:`BadRefError`."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, timeout=30, cwd=root)
        if diff.returncode != 0:
            raise BadRefError(
                "cannot resolve ref %r: %s"
                % (ref, diff.stderr.strip().splitlines()[0]
                   if diff.stderr.strip() else "git diff failed"))
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, timeout=30, cwd=root)
        names = diff.stdout.splitlines()
        if untracked.returncode == 0:
            names += untracked.stdout.splitlines()
        return {os.path.abspath(os.path.join(root, n))
                for n in names if n.endswith(".py")}
    except (OSError, subprocess.SubprocessError):
        return None


def _sarif_doc(findings):
    """Findings -> a SARIF 2.1.0 log dict (stable ordering: findings
    arrive sorted, the rule table is sorted by id)."""
    from veles.analysis.core import RULES
    seen_rules = sorted({f.rule for f in findings})
    rules = []
    for rule_id in seen_rules:
        _fn, severity, doc = RULES.get(rule_id, (None, "error", ""))
        rules.append({
            "id": rule_id,
            "shortDescription": {"text": doc},
            "defaultConfiguration": {
                "level": "error" if severity == "error"
                else "warning"},
        })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": "%s (hint: %s)" % (f.message,
                                                   f.hint)},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.file.replace(os.sep, "/")},
                    "region": {"startLine": f.line},
                },
            }],
        })
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "zlint",
                                "rules": rules}},
            "results": results,
        }],
    }


def _stats_table(stats):
    """``--stats`` rows -> an aligned text table (slowest first) with
    a totals line."""
    lines = ["%-24s %8s %9s %7s %7s"
             % ("rule", "seconds", "findings", "fresh", "cached")]
    for row in sorted(stats, key=lambda r: -r["seconds"]):
        lines.append("%-24s %8.4f %9d %7d %7d"
                     % (row["rule"], row["seconds"],
                        row["findings"], row["fresh_modules"],
                        row["cached_modules"]))
    lines.append("%-24s %8.4f %9d"
                 % ("total", sum(r["seconds"] for r in stats),
                    sum(r["findings"] for r in stats)))
    return "\n".join(lines)


def lint_main(argv=None):
    from veles.analysis.core import (
        RULES, UnknownRuleError, _load_rules, analyze_paths,
        iter_py_files)
    p = argparse.ArgumentParser(
        prog="velescli lint",
        description="Framework-aware static analysis (zlint): tracer "
                    "purity, lock order, checkpoint completeness, "
                    "telemetry hygiene, thread lifecycle, wire-frame "
                    "schemas, resource leaks, loop exception safety "
                    "+ generic hygiene. Suppress a finding with "
                    "`# zlint: disable=RULE (reason)` on its line.")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories (default: the veles "
                        "package)")
    p.add_argument("--format", default=None, metavar="FMT",
                   choices=("text", "json", "sarif"),
                   help="output format: text (default), json "
                        "(sorted array), sarif (SARIF 2.1.0 for CI/"
                        "editor ingestion); all byte-stable")
    p.add_argument("--json", action="store_true",
                   help="alias for --format json")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run (default: "
                        "all)")
    p.add_argument("--changed-only", nargs="?", const="HEAD",
                   default=None, metavar="REF",
                   help="lint only files changed vs REF (default "
                        "HEAD; untracked files included) — the fast "
                        "pre-commit mode. Falls back to the full "
                        "tree with a warning when git is "
                        "unavailable. Note: cross-file context "
                        "shrinks to the changed set — combine with "
                        "--cache to keep the FULL tree and let "
                        "unchanged modules answer from cache instead")
    p.add_argument("--cache", default=None, metavar="DIR",
                   help="incremental analysis cache directory: "
                        "per-rule results keyed by content hashes "
                        "over each module's import closure (see "
                        "veles/analysis/cache.py) — warm full-tree "
                        "runs re-analyze only what changed, with "
                        "byte-identical output")
    p.add_argument("--stats", action="store_true",
                   help="per-rule wall time, finding counts and "
                        "fresh/cached module counts; text appends a "
                        "table, json wraps the array as {findings, "
                        "stats}, sarif prints the table to stderr")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    try:
        args = p.parse_args(argv)
    except SystemExit as exc:
        # argparse exits 2 on usage errors already; normalize others
        return int(exc.code or 0)
    if args.list_rules:
        _load_rules()
        for rule_id in sorted(RULES):
            _fn, sev, doc = RULES[rule_id]
            print("%-24s %-8s %s" % (rule_id, sev, doc))
        return 0
    fmt = args.format or ("json" if args.json else "text")
    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",")
                  if r.strip()]
    paths = args.paths or _default_paths()
    cache = None
    if args.cache:
        from veles.analysis.cache import AnalysisCache
        try:
            cache = AnalysisCache(args.cache)
        except OSError as exc:
            print("error: cannot use cache dir %s: %s"
                  % (args.cache, exc), file=sys.stderr)
            return 2
    stats = [] if args.stats else None
    try:
        if args.changed_only is not None:
            try:
                changed = _changed_files(args.changed_only)
            except BadRefError as exc:
                print("error: --changed-only: %s" % exc,
                      file=sys.stderr)
                return 2
            if changed is None:
                print("warning: --changed-only: git unavailable — "
                      "linting the full tree", file=sys.stderr)
            elif cache is not None:
                # with a cache the full tree IS the fast path:
                # unchanged modules answer from cache, and the lint
                # keeps its complete cross-file view instead of
                # narrowing context to the changed set
                pass
            else:
                paths = [f for f in iter_py_files(paths)
                         if os.path.abspath(f) in changed]
        findings = analyze_paths(paths, select=select, cache=cache,
                                 stats=stats)
    except FileNotFoundError as exc:
        print("error: no such file or directory: %s" % exc,
              file=sys.stderr)
        return 2
    except UnknownRuleError as exc:
        print("error: %s" % exc, file=sys.stderr)
        return 2
    except (SyntaxError, UnicodeDecodeError) as exc:
        # an unparseable input is a usage error (2), NOT "findings"
        # (1): CI diffing on exit codes must never read a crashed
        # lint as a lint verdict
        print("error: cannot parse %s: %s"
              % (getattr(exc, "filename", "input"), exc),
              file=sys.stderr)
        return 2
    except OSError as exc:
        # unreadable input (permissions, transient FS trouble) is an
        # environment error, same contract as above
        print("error: cannot read input: %s" % exc, file=sys.stderr)
        return 2
    if fmt == "json":
        if stats is not None:
            print(json.dumps({"findings": [f.as_dict()
                                           for f in findings],
                              "stats": stats}, indent=2))
        else:
            print(json.dumps([f.as_dict() for f in findings],
                             indent=2))
    elif fmt == "sarif":
        _load_rules()
        print(json.dumps(_sarif_doc(findings), indent=2,
                         sort_keys=True))
        if stats is not None:
            # the SARIF document must stay pure: the human-facing
            # table goes to stderr
            print(_stats_table(stats), file=sys.stderr)
    else:
        for f in findings:
            print(f.render())
        print("%d finding(s)" % len(findings))
        if stats is not None:
            print(_stats_table(stats))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(lint_main())
