"""``velescli lint`` — run zlint over files/directories.

Exit codes follow the gate contract: **0** clean, **1** findings,
**2** usage error (bad path, unknown rule). ``--json`` emits the
findings as a JSON array sorted by (file, line, rule) with
repo-relative paths — byte-stable for CI diffing.
"""

import argparse
import json
import os
import sys


def _default_paths():
    """With no paths given, lint the installed veles package."""
    import veles
    return [os.path.dirname(os.path.abspath(veles.__file__))]


def lint_main(argv=None):
    from veles.analysis.core import (
        RULES, UnknownRuleError, _load_rules, analyze_paths)
    p = argparse.ArgumentParser(
        prog="velescli lint",
        description="Framework-aware static analysis (zlint): tracer "
                    "purity, lock order, checkpoint completeness, "
                    "telemetry hygiene, thread lifecycle + generic "
                    "hygiene. Suppress a finding with "
                    "`# zlint: disable=RULE (reason)` on its line.")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories (default: the veles "
                        "package)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable sorted JSON findings")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run (default: "
                        "all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    try:
        args = p.parse_args(argv)
    except SystemExit as exc:
        # argparse exits 2 on usage errors already; normalize others
        return int(exc.code or 0)
    if args.list_rules:
        _load_rules()
        for rule_id in sorted(RULES):
            _fn, sev, doc = RULES[rule_id]
            print("%-24s %-8s %s" % (rule_id, sev, doc))
        return 0
    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",")
                  if r.strip()]
    try:
        findings = analyze_paths(args.paths or _default_paths(),
                                 select=select)
    except FileNotFoundError as exc:
        print("error: no such file or directory: %s" % exc,
              file=sys.stderr)
        return 2
    except UnknownRuleError as exc:
        print("error: %s" % exc, file=sys.stderr)
        return 2
    except (SyntaxError, UnicodeDecodeError) as exc:
        # an unparseable input is a usage error (2), NOT "findings"
        # (1): CI diffing on exit codes must never read a crashed
        # lint as a lint verdict
        print("error: cannot parse %s: %s"
              % (getattr(exc, "filename", "input"), exc),
              file=sys.stderr)
        return 2
    except OSError as exc:
        # unreadable input (permissions, transient FS trouble) is an
        # environment error, same contract as above
        print("error: cannot read input: %s" % exc, file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print("%d finding(s)" % len(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(lint_main())
