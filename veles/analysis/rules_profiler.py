"""profiler-safety: profile captures must stay off the reactor loop.

The sampling profiler (``veles/profiling.py``) BLOCKS for the whole
requested capture window — ``capture_profile``/``profile_endpoint``
sleep out ``seconds`` of wall time while the sampler thread walks
stacks. Run on the shared reactor loop, one profile request would
park every connection, probe and timer for seconds (exactly the
failure the loop-lag gauge exists to catch). This rule statically
checks the two places that could make that mistake:

* **``/debug/profile`` route branches**: any ``if``/``elif`` branch
  whose test mentions the ``"/debug/profile"`` string constant (the
  routing convention in ``web_status.py`` and the serving frontend)
  must hand the work to a worker thread — the branch has to contain a
  ``.defer(...)`` call, and must not call a capture primitive
  directly (``capture_profile``/``profile_endpoint``, or
  ``.start()``/``.stop()``/``.capture()`` on a profiler-named
  receiver). Calls inside a nested ``def``/``lambda`` are exempt:
  that is the deferred body itself.
* **reactor callbacks**: the same capture primitives are banned
  inside the shared :func:`veles.analysis.engine.reactor_callbacks`
  contexts (``on_frame``/``on_timer`` methods,
  ``call_soon``/``call_later``/``every``/``post`` targets).
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

#: module-level capture primitives (veles/profiling.py public API)
_CAPTURE_CALLS = frozenset(("capture_profile", "profile_endpoint"))

#: methods that start/stop/collect a capture when the receiver is
#: profiler-shaped (``profiler.start()``, ``self._profiler.stop()``)
_PROFILER_METHODS = frozenset(("start", "stop", "capture"))

#: the route string this rule keys branch detection on (a module
#: constant, not an inline literal: the rule must not fire on its own
#: matcher)
_ROUTE_MARK = "/debug" + "/profile"


def _capture_call(node):
    """The capture primitive ``node`` invokes, or None."""
    name = engine.call_name(node)
    if name in _CAPTURE_CALLS:
        return name
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _PROFILER_METHODS \
            and "profil" in engine.receiver_name(
                node.func.value).lower():
        return "%s.%s" % (engine.receiver_name(node.func.value),
                          node.func.attr)
    return None


def _scan_route_branch(mod, test, body, findings):
    """Calls inside nested def/lambda bodies are exempt via the
    shared scoped walk: a deferred closure's body runs on a worker
    thread — the compliant escape, not a violation."""
    has_defer = []
    captures = []
    for stmt in body:
        for call in engine.iter_calls(stmt):
            if engine.call_name(call) == "defer":
                has_defer.append(call)
            cap = _capture_call(call)
            if cap is not None:
                captures.append((call, cap))
    for call, cap in captures:
        findings.append(Finding(
            mod.relpath, call.lineno, "profiler-safety", "error",
            "capture primitive %r called directly in a "
            "/debug/profile route branch — the capture blocks for "
            "the whole requested window on the reactor loop" % cap,
            "hand the capture to a worker thread: "
            "request.defer(handler, request), reply from there"))
    if not has_defer and not captures:
        findings.append(Finding(
            mod.relpath, test.lineno, "profiler-safety", "error",
            "/debug/profile route branch contains no .defer(...) "
            "call — the profile capture blocks for seconds and must "
            "never answer inline on the reactor loop",
            "route the branch through request.defer(...) and run "
            "profile_endpoint on the worker thread"))


def _scan_callback(mod, node, where, findings, seen):
    for sub, cap in engine.novel_calls(mod, node, seen,
                                       _capture_call):
        findings.append(Finding(
            mod.relpath, sub.lineno, "profiler-safety", "error",
            "profiler capture %r inside reactor callback %s — the "
            "capture blocks for its whole window and parks every "
            "connection, probe and timer with it" % (cap, where),
            "move the capture to a worker thread (request.defer / "
            "a plain Thread) and reply via call_soon"))


@register("profiler-safety", "error",
          "/debug/profile route branches must request.defer their "
          "capture, and profiler start/stop/capture_profile are "
          "banned inside reactor callbacks — a capture blocks for "
          "its whole window")
def check_profiler_safety(project):
    findings = []
    seen = set()
    for mod in project.modules:
        # 1) /debug/profile route branches
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.If) \
                    and engine.test_mentions(node.test,
                                             (_ROUTE_MARK,)):
                _scan_route_branch(mod, node.test, node.body,
                                   findings)
    # 2) reactor callbacks (the shared loop-context enumeration)
    for mod, _cls, func, where in engine.reactor_callbacks(project):
        _scan_callback(mod, func, where, findings, seen)
    return findings
