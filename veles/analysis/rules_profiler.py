"""profiler-safety: profile captures must stay off the reactor loop.

The sampling profiler (``veles/profiling.py``) BLOCKS for the whole
requested capture window — ``capture_profile``/``profile_endpoint``
sleep out ``seconds`` of wall time while the sampler thread walks
stacks. Run on the shared reactor loop, one profile request would
park every connection, probe and timer for seconds (exactly the
failure the loop-lag gauge exists to catch). This rule statically
checks the two places that could make that mistake:

* **``/debug/profile`` route branches**: any ``if``/``elif`` branch
  whose test mentions the ``"/debug/profile"`` string constant (the
  routing convention in ``web_status.py`` and the serving frontend)
  must hand the work to a worker thread — the branch has to contain a
  ``.defer(...)`` call, and must not call a capture primitive
  directly (``capture_profile``/``profile_endpoint``, or
  ``.start()``/``.stop()``/``.capture()`` on a profiler-named
  receiver). Calls inside a nested ``def``/``lambda`` are exempt:
  that is the deferred body itself.
* **reactor callbacks**: the same capture primitives are banned
  inside ``on_frame``/``on_timer`` methods and
  ``call_soon``/``call_later``/``every`` targets, reusing the
  ``reactor-purity`` rule's target resolution.
"""

import ast

from veles.analysis.core import Finding, register
from veles.analysis.rules_reactor import (
    _CALLBACK_METHODS, _SCHEDULE_CALLS, _call_name, _resolve_target,
    _walk_scopes)

#: module-level capture primitives (veles/profiling.py public API)
_CAPTURE_CALLS = frozenset(("capture_profile", "profile_endpoint"))

#: methods that start/stop/collect a capture when the receiver is
#: profiler-shaped (``profiler.start()``, ``self._profiler.stop()``)
_PROFILER_METHODS = frozenset(("start", "stop", "capture"))

#: the route string this rule keys branch detection on (a module
#: constant, not an inline literal: the rule must not fire on its own
#: matcher)
_ROUTE_MARK = "/debug" + "/profile"


def _receiver_name(node):
    """The rightmost name of a call receiver: ``a.b.profiler`` ->
    'profiler', ``profiler`` -> 'profiler', else ''."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _receiver_name(node.func)
    return ""


def _capture_call(node):
    """The capture primitive ``node`` invokes, or None."""
    name = _call_name(node)
    if name in _CAPTURE_CALLS:
        return name
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _PROFILER_METHODS \
            and "profil" in _receiver_name(
                node.func.value).lower():
        return "%s.%s" % (_receiver_name(node.func.value),
                          node.func.attr)
    return None


def _tests_profile_route(test):
    """True when an if-test mentions the "/debug/profile" constant
    (``==``, ``startswith``, tuple membership — any spelling)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and _ROUTE_MARK in sub.value:
            return True
    return False


def _walk_branch(nodes, on_call):
    """Walk statement bodies without descending into nested function
    or lambda definitions (a deferred closure's body runs on a worker
    thread — the compliant escape, not a violation)."""
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            on_call(node)
        _walk_branch(list(ast.iter_child_nodes(node)), on_call)


def _scan_route_branch(mod, test, body, findings):
    has_defer = []
    captures = []

    def on_call(call):
        name = _call_name(call)
        if name == "defer":
            has_defer.append(call)
        cap = _capture_call(call)
        if cap is not None:
            captures.append((call, cap))

    _walk_branch(body, on_call)
    for call, cap in captures:
        findings.append(Finding(
            mod.relpath, call.lineno, "profiler-safety", "error",
            "capture primitive %r called directly in a "
            "/debug/profile route branch — the capture blocks for "
            "the whole requested window on the reactor loop" % cap,
            "hand the capture to a worker thread: "
            "request.defer(handler, request), reply from there"))
    if not has_defer and not captures:
        findings.append(Finding(
            mod.relpath, test.lineno, "profiler-safety", "error",
            "/debug/profile route branch contains no .defer(...) "
            "call — the profile capture blocks for seconds and must "
            "never answer inline on the reactor loop",
            "route the branch through request.defer(...) and run "
            "profile_endpoint on the worker thread"))


def _scan_callback(mod, node, where, findings, seen):
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        cap = _capture_call(sub)
        if cap is None:
            continue
        key = (mod.relpath, sub.lineno, cap)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            mod.relpath, sub.lineno, "profiler-safety", "error",
            "profiler capture %r inside reactor callback %s — the "
            "capture blocks for its whole window and parks every "
            "connection, probe and timer with it" % (cap, where),
            "move the capture to a worker thread (request.defer / "
            "a plain Thread) and reply via call_soon"))


@register("profiler-safety", "error",
          "/debug/profile route branches must request.defer their "
          "capture, and profiler start/stop/capture_profile are "
          "banned inside reactor callbacks — a capture blocks for "
          "its whole window")
def check_profiler_safety(project):
    findings = []
    seen = set()
    for mod in project.modules:
        # 1) /debug/profile route branches
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.If) \
                    and _tests_profile_route(node.test):
                _scan_route_branch(mod, node.test, node.body,
                                   findings)
        # 2) reactor callbacks (same contexts reactor-purity scans)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and item.name in _CALLBACK_METHODS:
                        _scan_callback(
                            mod, item,
                            "%s.%s" % (node.name, item.name),
                            findings, seen)
        calls = []
        _walk_scopes(mod.tree, None, [], calls)
        for call, cls_node, func_stack in calls:
            pos = _SCHEDULE_CALLS[_call_name(call)]
            if len(call.args) <= pos:
                continue
            target, desc = _resolve_target(
                call.args[pos], mod, cls_node, func_stack)
            if target is not None:
                _scan_callback(mod, target,
                               "%s (scheduled at line %d)"
                               % (desc, call.lineno), findings, seen)
    return findings
