"""Taint rules: untrusted wire/HTTP input must not steer resources.

The runtime is a trust-boundary factory — pickled master<->slave
frames, a public HTTP plane, environment overrides — and "validated
at admission" is a convention until something enforces it. These
rules sit on :func:`veles.analysis.engine.taint_hits`, the shared
whole-program taint pass, and turn each sink category into a finding:

* ``untrusted-geometry`` — a wire/HTTP-derived value sizes an
  allocation (``zeros``/``empty``/``arange``/``bytearray`` extents,
  ``range`` trip counts, ``[x] * n`` repetition): a client-chosen
  integer becomes memory or iterations;
* ``unbounded-cardinality`` — a persistent container (``self.X`` /
  module global) is keyed by a wire/HTTP/env value without a bounded
  resolver: callers mint entries that live forever (the generalized
  form of telemetry-hygiene's wire-label check, for ANY dict/set);
* ``unsafe-deserialize`` — ``pickle.loads``/``marshal.loads`` of
  untrusted bytes not dominated by ``hmac.compare_digest``: code
  execution for whoever can reach the socket;
* ``untrusted-path`` — a wire/HTTP value reaches a filesystem call or
  a ``checkpoint=``/``store=``-style target keyword: clients choose
  what the server opens.

Sanitizers the engine recognizes (see the engine docstring):
``*resolve*``/``*clamp*``/``*validate*``/``*sanitize*``-named calls,
``# zlint: sanitizer``-annotated defs (and ``Bounded*``/annotated
container classes), explicit comparison/membership/isinstance guards,
``min()`` against an untainted bound, and HMAC-verify domination for
the deserialize sink.
"""

from veles.analysis.core import Finding, register
from veles.analysis.engine import taint_hits

#: sink category -> (rule id, message template, hint)
_SINKS = {
    "geometry": (
        "untrusted-geometry",
        "allocation geometry from %s input: %s — a client-chosen "
        "number becomes memory/iterations",
        "clamp against a server-side bound (min(x, CAP) or an "
        "explicit comparison guard) before it sizes anything, or "
        "route it through a *validate*/*clamp* helper"),
    "cardinality": (
        "unbounded-cardinality",
        "persistent container keyed by %s input: %s — every novel "
        "value is a new entry that lives forever",
        "fold keys through a bounded resolver (e.g. "
        "tenants.TenantTable.resolve) or store them in a capped "
        "container class (Bounded*/# zlint: sanitizer annotated)"),
    "deserialize": (
        "unsafe-deserialize",
        "%s-derived bytes reach %s without HMAC verification — "
        "arbitrary object construction for whoever reaches the "
        "socket",
        "verify hmac.compare_digest over the exact framed bytes "
        "before decoding (see server.recv_frame), or switch to a "
        "data-only codec"),
    "path": (
        "untrusted-path",
        "filesystem/store target from %s input: %s — clients choose "
        "what the server opens",
        "resolve the name against a server-side registry/allowlist "
        "(a *resolve*-named or # zlint: sanitizer helper) before it "
        "touches storage"),
}


def _chain_suffix(chain):
    if len(chain) <= 1:
        return ""
    return " (via %s)" % " -> ".join(chain)


def _findings_for(project, sink):
    rule_id, template, hint = _SINKS[sink]
    out = []
    for hit in taint_hits(project):
        if hit.sink != sink:
            continue
        kinds = "+".join(sorted(hit.kinds))
        out.append(Finding(
            hit.module.relpath, hit.lineno, rule_id, "error",
            (template % (kinds, hit.detail)) + _chain_suffix(
                hit.chain),
            hint))
    return out


@register("untrusted-geometry", "error",
          "no wire/HTTP-derived value may size an allocation or a "
          "loop without a clamp")
def check_untrusted_geometry(project):
    return _findings_for(project, "geometry")


@register("unbounded-cardinality", "error",
          "no persistent dict/set keyed by unresolved wire/HTTP/env "
          "values")
def check_unbounded_cardinality(project):
    return _findings_for(project, "cardinality")


@register("unsafe-deserialize", "error",
          "no pickle/marshal decode of untrusted bytes outside HMAC "
          "verification")
def check_unsafe_deserialize(project):
    return _findings_for(project, "deserialize")


@register("untrusted-path", "error",
          "no wire/HTTP-derived filesystem or checkpoint/store "
          "targets without registry resolution")
def check_untrusted_path(project):
    return _findings_for(project, "path")
