"""zlint — framework-aware static analysis for the veles tree.

The runtime grew into a threaded, fault-tolerant, checkpointed system
(master/slave leases, persist + heartbeat threads, micro-batcher,
durable snapshotter) whose correctness rests on invariants no test
exercises exhaustively: lock acquisition order, tracer purity of the
jit-compiled step functions, and the ``get_state``/``checkpoint_state``
protocol that silently drops any unit which forgets to implement it.
This package machine-checks those invariants over the AST:

========================  =============================================
rule id                   checks
========================  =============================================
``tracer-purity``         ``xla_run`` closures (the functions
                          StepCompiler traces under ``jax.jit``) must
                          not call ``numpy.random``/``time.*``/
                          ``print``, concretize traced values
                          (``.item()``, ``float()``/``int()`` on a
                          ``ctx`` read) or mutate ``self``
``lock-order``            inter-procedural lock-acquisition graph;
                          cycles = potential deadlocks, nested
                          re-acquisition of a non-reentrant ``Lock``
``unguarded-shared-state``  instance attributes written both from a
                          ``threading.Thread`` target and from
                          unlocked public methods
``checkpoint-state``      Unit subclasses whose ``run()`` mutates
                          instance state must implement ``get_state``/
                          ``checkpoint_state`` (or carry a pragma
                          explaining why the state is ephemeral)
``telemetry-hygiene``     instrument families created inside loops;
                          unbounded label values minted from ids
``probe-purity``          ``/healthz``/``/readyz`` handler branches
                          read cached state only — no locks, no
                          network, no live state pulls
``reactor-purity``        reactor callbacks (``on_frame``/
                          ``on_timer``, ``call_soon``/``call_later``/
                          ``every`` targets) must not call blocking
                          primitives — raw-socket ``recv``/
                          ``sendall``/``accept``, ``time.sleep``,
                          ``Event.wait``/``Thread.join``, ``urlopen``
``profiler-safety``       ``/debug/profile`` route branches must
                          ``request.defer`` their capture; profiler
                          ``start``/``stop``/``capture_profile`` are
                          banned inside reactor callbacks (a capture
                          blocks for its whole window)
``wire-schema``           producers and consumers of one wire-frame
                          (direction, kind) must agree on arity:
                          unguarded tuple unpacks, ``resp[:N]``
                          slices and ``V[i]`` reads are checked
                          against every tuple the other side ships
                          (mixed-version ``len()`` guards count as
                          safe)
``resource-leak``         acquired resources (sockets, registries,
                          KV slot grants, ``start_background``
                          servers) must be released on every path,
                          exception edges included
``loop-exception-safety``  call chains reachable from reactor
                          callbacks must not raise exception types
                          no frame on the chain catches
``stats-cadence``         in-graph model-stat outputs (the
                          model-health plane's per-layer vectors)
                          materialize on the host only behind the
                          ``stats_due`` cadence gate — never per step
``thread-lifecycle``      threads must be daemons or have a join path
``untrusted-geometry``    wire/HTTP-tainted values must not size
                          allocations (``zeros``/``bytearray``/
                          ``range`` args, ``shape=``/``maxlen=``
                          keywords, ``[0] * n``)
``unbounded-cardinality``  tainted values must not key growth of
                          persistent containers — route the key
                          through a bounded resolver
``unsafe-deserialize``    ``pickle.loads``/``marshal.loads`` on a
                          tainted payload not dominated by an
                          ``hmac.compare_digest`` verification
``untrusted-path``        tainted values must not reach filesystem/
                          store targets without an admission
                          resolver
``bare-except``           ``except:`` swallows ``KeyboardInterrupt``
``unused-import``         dead module-level imports
``unused-variable``       locals assigned and never read
========================  =============================================

All rules resolve calls through ONE shared whole-program engine
(``veles/analysis/engine.py``): an interprocedural call graph over
the parsed project (``self.method``, attribute type bindings,
module-alias and symbol-import resolution) plus a generic
forward-dataflow fixpoint (``ForwardDataflow``) and the shared
reactor-callback enumeration. Writing a new rule against the graph
is ~50 lines: resolve calls with ``CallGraph.resolve``, or subclass
``ForwardDataflow`` when a fact must flow caller→callee.

The four taint rules share one interprocedural taint pass
(``engine.taint_hits``): wire handler parameters, HTTP request
reads and env lookups are sources; sanitizer-named calls
(``*resolve*``/``*validate*``/``*clamp*``/``*sanitize*``), defs and
classes annotated ``# zlint: sanitizer (reason)``, and explicit
comparison guards kill taint; sinks are allocation geometry,
persistent-container growth, un-verified deserialization and
filesystem targets.

Findings carry file:line, rule id, severity and a one-line fix hint.
A finding is suppressed by a pragma comment on its line::

    self.reached = True   # zlint: disable=checkpoint-state (per-run)

``# zlint: disable=all`` silences every rule on that line. Run it as
``velescli lint [--format text|json|sarif] [--changed-only [REF]]
[--cache DIR] [--stats] [paths...]`` (exit 0 clean / 1 findings / 2
usage error). ``--cache DIR`` is the incremental mode
(``veles/analysis/cache.py``): per-rule results keyed by content
hashes over each module's import closure, so warm full-tree runs
re-analyze only what changed with byte-identical output — the
documented pre-commit line is ``velescli lint --changed-only --cache
.zlint-cache --format sarif``. The tier-1 gate
``tests/test_analysis.py`` keeps the whole ``veles/`` package (plus
``bench.py``) at zero findings, and ``bench.py`` tracks the
analyzer's own cold/warm full-tree wall time as
``lint_full_tree_seconds`` / ``lint_full_tree_warm_seconds``.
"""

from veles.analysis.core import (          # noqa: F401  (public API)
    Finding, Project, RULES, analyze_paths, iter_py_files)
