"""probe-purity: /healthz and /readyz handlers must never block.

Kubernetes-style probes are only useful if they answer while the
process is BUSY — a liveness check that queues behind the master
request lock times out exactly when the operator most needs it, and a
readiness handler that scans a registry or touches the network turns
every prober into load. The health plane's contract
(``veles/health.py``) is therefore: all real evaluation happens on
the monitor's sampler thread, and the HTTP probe branch reads ONE
cached attribute.

This rule finds the probe branches — any ``if``/``elif`` whose test
mentions a ``"/healthz"`` or ``"/readyz"`` string constant — and
flags blocking work inside them:

* ``with`` statements (lock acquisition, file/socket context
  managers: anything context-managed is a resource wait);
* explicit ``.acquire()`` / ``.wait()`` / ``.join()`` calls;
* network/file primitives (``urlopen``, ``create_connection``,
  ``connect``, ``recv*``, ``open``, ``sleep``);
* live state pulls (``.status()``, ``.snapshot()``, ``.metrics()``,
  ``.describe()``) — the pull belongs on the monitor thread, the
  handler serves the cached verdict.
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

_PROBE_MARKERS = ("/healthz", "/readyz")

#: attribute/function call names that block or pull live state
_BLOCKING_CALLS = frozenset((
    "acquire", "wait", "join", "sleep",
    "urlopen", "urlretrieve", "create_connection", "connect",
    "getaddrinfo", "recv", "recv_into", "makefile", "open",
    "status", "snapshot", "metrics", "describe",
))


def _scan_branch(mod, body, findings):
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                findings.append(Finding(
                    mod.relpath, node.lineno, "probe-purity", "error",
                    "context-managed resource acquisition inside a "
                    "/healthz// readyz branch — a probe that waits "
                    "on a lock or I/O times out exactly when the "
                    "process is busiest",
                    "serve the health monitor's cached verdict "
                    "(HealthMonitor.probe reads one attribute); do "
                    "the real work on the monitor's sampler thread"))
            elif isinstance(node, ast.Call):
                name = engine.call_name(node)
                if name in _BLOCKING_CALLS:
                    findings.append(Finding(
                        mod.relpath, node.lineno, "probe-purity",
                        "error",
                        "blocking or live-state call %r inside a "
                        "/healthz//readyz branch — probes must read "
                        "cached state only, never take the master "
                        "lock or touch the network" % name,
                        "move the %s() evaluation into a readiness "
                        "check on the health monitor's sampler "
                        "thread and serve the cached result here"
                        % name))


@register("probe-purity", "error",
          "/healthz and /readyz handler branches read cached state "
          "only — no locks, no network, no live state pulls",
          scope="module")
def check_probe_purity(project):
    findings = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.If) \
                    and engine.test_mentions(node.test,
                                             _PROBE_MARKERS):
                _scan_branch(mod, node.body, findings)
    return findings
