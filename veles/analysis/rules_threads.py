"""Concurrency rules: lock-order, unguarded-shared-state,
thread-lifecycle.

``lock-order`` builds an inter-procedural lock-acquisition graph: a
``with self._lock:`` (or a module-global lock) puts that lock on the
held stack, and every lock acquired while another is held records an
ordering edge. Calls are followed through the shared
:class:`veles.analysis.engine.CallGraph` — ``self.method()``,
``self.attr.method()`` when ``__init__`` bound the attr to a project
class, module-level functions, imported symbols and constructor
calls — so a nesting like ``MasterServer.persist_state (holds
_persist_lock) -> checkpoint_state (takes lock)`` shows up as the
edge ``_persist_lock -> lock`` even though no single function
acquires both. Cycles in the merged graph are potential deadlocks;
re-entering a non-reentrant ``threading.Lock`` (directly or through
calls) is reported even without a cycle. ``threading.Condition(lock)``
aliases its lock; ``.wait()`` is not an acquisition.

``unguarded-shared-state`` flags instance attributes written both from
thread-side code (a ``Thread(target=...)`` method or a nested function
handed to a Thread) and from an unlocked public method — the classic
"constructor-started background thread vs. API caller" race.

``thread-lifecycle`` requires every started thread to be a daemon or
to have a visible ``.join()`` path, so interpreter shutdown can never
hang on a forgotten worker.
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

_MAX_DEPTH = engine.MAX_DEPTH


class _LockWalker:
    """Inter-procedural walk collecting lock-ordering edges; call
    resolution is the shared engine CallGraph."""

    def __init__(self, project):
        self.project = project
        self.graph = engine.CallGraph(project)
        #: (lock_a, lock_b) -> (module, lineno, "Class.meth -> ...")
        self.edges = {}
        #: re-entry of a non-reentrant lock: [(lock, module, lineno,
        #: chain)]
        self.reentries = []
        self._active = []      # call-stack guard: (id(func), lockset)
        self._cls_locks = {}   # id(ClassInfo) -> (locks, aliases)

    def _locks_for(self, cls):
        """Hierarchy-merged (locks, aliases) for a class, cached."""
        got = self._cls_locks.get(id(cls))
        if got is None:
            got = self._cls_locks[id(cls)] = \
                self.project.class_locks(cls)
        return got

    def _lock_id(self, ctx_mod, ctx_cls, expr):
        """The (owner, attr) lock node for a ``with`` context
        expression, or None when it is not a recognizable lock."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and ctx_cls is not None:
            locks, aliases = self._locks_for(ctx_cls)
            attr = expr.attr
            # chase Condition->lock aliases within the hierarchy
            seen = set()
            while attr in aliases and attr not in seen:
                seen.add(attr)
                attr = aliases[attr]
            if attr in locks:
                owner, kind = locks[attr]
                # key by the DEFINING class so Base and Child uses
                # of one inherited lock unify into one graph node
                return ((owner, attr), kind)
            return None
        if isinstance(expr, ast.Name) \
                and expr.id in ctx_mod.global_locks:
            return (("module:" + ctx_mod.relpath, expr.id),
                    ctx_mod.global_locks[expr.id])
        return None

    # -- the walk ------------------------------------------------------

    def walk_function(self, mod, cls, func, held, chain):
        key = (id(func), frozenset(lock for lock, _ in held))
        if key in self._active or len(self._active) > _MAX_DEPTH:
            return
        self._active.append(key)
        try:
            for stmt in func.body:
                self._walk_stmt(mod, cls, stmt, held, chain)
        finally:
            self._active.pop()

    def _walk_stmt(self, mod, cls, node, held, chain):
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                # earlier items of the SAME statement are already
                # held: `with self.a, self.b:` orders a before b, and
                # `with self.a, self.a:` deadlocks exactly like the
                # nested spelling
                cur_held = held + acquired
                got = self._lock_id(mod, cls, item.context_expr)
                if got is None:
                    self._walk_expr(mod, cls, item.context_expr,
                                    cur_held, chain)
                    continue
                lock, kind = got
                held_locks = [h for h, _ in cur_held]
                if lock in held_locks:
                    if kind == "lock":
                        self.reentries.append(
                            (lock, mod, node.lineno, list(chain)))
                else:
                    for h, _site in cur_held:
                        self.edges.setdefault(
                            (h, lock),
                            (mod, node.lineno, " -> ".join(chain)))
                    acquired.append((lock, (mod, node.lineno)))
            inner = held + acquired
            for stmt in node.body:
                self._walk_stmt(mod, cls, stmt, inner, chain)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return      # nested defs execute later, not here
        for kind, child in engine.iter_stmt_children(node):
            if kind == "stmt":
                self._walk_stmt(mod, cls, child, held, chain)
            else:
                self._walk_expr(mod, cls, child, held, chain)

    def _walk_expr(self, mod, cls, node, held, chain):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            target = self.graph.resolve(mod, cls, sub)
            if target is None:
                continue
            self.walk_function(target.module, target.cls, target.func,
                               held, chain + [target.label])


def _fmt_lock(lock):
    owner, attr = lock
    return "%s.%s" % (owner, attr)


@register("lock-order", "error",
          "lock-acquisition-order cycles (potential deadlocks) and "
          "re-entry of non-reentrant locks")
def check_lock_order(project):
    walker = _LockWalker(project)
    for mod in project.modules:
        for func in mod.functions.values():
            walker.walk_function(mod, None, func, [], [func.name])
        for cls in mod.classes.values():
            for mname, meth in cls.methods.items():
                walker.walk_function(
                    mod, cls, meth, [], ["%s.%s" % (cls.name, mname)])
    findings = []
    for lock, mod, lineno, chain in walker.reentries:
        findings.append(Finding(
            mod.relpath, lineno, "lock-order", "error",
            "non-reentrant lock %s re-acquired while already held "
            "(via %s) — this deadlocks at runtime"
            % (_fmt_lock(lock), " -> ".join(chain)),
            "use threading.RLock, or split the locked region so the "
            "outer caller passes already-held state in"))
    for comp in engine.tarjan_sccs(walker.edges):
        comp_set = set(comp)
        sites = []
        for (a, b), (mod, lineno, chain) in sorted(
                walker.edges.items(),
                key=lambda kv: (kv[1][0].relpath, kv[1][1])):
            if a in comp_set and b in comp_set:
                sites.append((a, b, mod, lineno, chain))
        if not sites:
            continue
        a, b, mod, lineno, chain = sites[0]
        order = ", ".join(
            "%s -> %s (%s:%d)" % (_fmt_lock(x), _fmt_lock(y),
                                  m.relpath, ln)
            for x, y, m, ln, _ in sites)
        findings.append(Finding(
            mod.relpath, lineno, "lock-order", "error",
            "lock-order cycle between {%s}: %s"
            % (", ".join(sorted(_fmt_lock(c) for c in comp)), order),
            "pick one global acquisition order and restructure the "
            "calls (move work outside the lock, or hand off through "
            "a queue/event instead of calling back under the lock)"))
    return findings


# -- unguarded-shared-state --------------------------------------------


def _self_writes(func, lock_attrs, alias_attrs):
    """[(attr, lineno, under_lock)] for direct self.X writes in
    ``func`` (``with self.<lock>`` scopes tracked lexically)."""
    out = []

    def walk(node, locked):
        if isinstance(node, ast.With):
            inner = locked
            for item in node.items:
                e = item.context_expr
                if isinstance(e, ast.Attribute) \
                        and isinstance(e.value, ast.Name) \
                        and e.value.id == "self":
                    attr = e.attr
                    attr = alias_attrs.get(attr, attr)
                    if attr in lock_attrs:
                        inner = True
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    out.append((t.attr, node.lineno, locked))
        for kind, child in engine.iter_stmt_children(node):
            if kind == "stmt":
                walk(child, locked)

    for stmt in func.body:
        walk(stmt, False)
    return out


def _thread_target_names(methods):
    """Names of methods / nested functions handed to
    ``threading.Thread(target=...)`` anywhere in ``methods`` (a
    hierarchy-merged {name: (owner, FunctionDef)} map — the thread
    may be started by a base class)."""
    targets = set()
    for _owner, meth in methods.values():
        for node in ast.walk(meth):
            if not isinstance(node, ast.Call):
                continue
            if engine.call_name(node) != "Thread":
                continue
            # target may be the keyword OR the second positional arg
            # (Thread(group, target, ...))
            values = [kw.value for kw in node.keywords
                      if kw.arg == "target"]
            if len(node.args) >= 2:
                values.append(node.args[1])
            for v in values:
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == "self":
                    targets.add(v.attr)
                elif isinstance(v, ast.Name):
                    targets.add(v.id)
    return targets


@register("unguarded-shared-state", "error",
          "instance attributes written both from a Thread target and "
          "from unlocked public methods", scope="module")
def check_unguarded_shared_state(project):
    findings = []
    seen = set()       # (file, line, attr): base races re-surface
    #                    when every subclass is scanned — report once
    for mod in project.modules:
        for cls in mod.classes.values():
            # hierarchy-merged view: the thread may be started by a
            # base class while the racing public method lives on the
            # subclass (or vice versa)
            methods = project.class_methods(cls)
            targets = _thread_target_names(methods)
            if not targets:
                continue
            locks, aliases = project.class_locks(cls)
            lock_attrs = set(locks)
            thread_writes = {}     # attr -> [(owner_mod, line, locked)]
            public_writes = {}     # attr -> [(owner_mod, line, locked, meth)]
            for mname, (owner, meth) in methods.items():
                omod = owner.module
                funcs = []
                nested = engine.nested_functions(meth)
                if mname in targets:
                    funcs.append(meth)
                funcs.extend(f for n, f in nested.items()
                             if n in targets)
                for func in funcs:
                    for attr, line, locked in _self_writes(
                            func, lock_attrs, aliases):
                        thread_writes.setdefault(attr, []).append(
                            (omod, line, locked))
                if mname in targets or mname.startswith("_"):
                    continue       # private / the thread body itself
                for attr, line, locked in _self_writes(
                        meth, lock_attrs, aliases):
                    public_writes.setdefault(attr, []).append(
                        (omod, line, locked, mname))
            for attr in sorted(set(thread_writes) & set(public_writes)):
                unlocked = [(om, ln, m) for om, ln, lk, m
                            in public_writes[attr] if not lk]
                unlocked_thread = [(om, ln) for om, ln, lk
                                   in thread_writes[attr] if not lk]
                if not unlocked and not unlocked_thread:
                    continue
                if unlocked:
                    omod, line, meth = unlocked[0]
                    where = "public method %s()" % meth
                else:
                    omod, line = unlocked_thread[0]
                    where = "the thread body"
                key = (omod.relpath, line, attr)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    omod.relpath, line, "unguarded-shared-state",
                    "error",
                    "%s.%s is written by a Thread target and by %s "
                    "without holding a lock" % (cls.name, attr, where),
                    "guard both writers with the owning lock (or "
                    "hand the value through a queue/Event)"))
    return findings


# -- thread-lifecycle --------------------------------------------------


def _joined_names(mod):
    """{key} of every ``<key>.join(...)`` call in the module — plus
    the ITERABLE's key when a for-loop joins its loop variable
    (``for t in threads: t.join()`` marks ``threads``), so the
    thread-pool idiom ``threads = [Thread(...) for ...]`` resolves."""
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            key = engine.target_key(node.func.value)
            if key is not None:
                out.add(key)
        elif isinstance(node, ast.For) \
                and isinstance(node.target, ast.Name):
            var = node.target.id
            iter_key = engine.target_key(node.iter)
            if iter_key is None:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "join" \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == var:
                    out.add(iter_key)
                    break
    return out


def _comprehension_target(mod, call):
    """The name a comprehension-built pool is assigned to when
    ``call`` is a constructor inside it (``threads =
    [Thread(...) for ...]`` -> "threads"), or None."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, (ast.ListComp,
                                            ast.GeneratorExp)) \
                and any(sub is call for sub in ast.walk(node.value)):
            return engine.target_key(node.targets[0])
    return None


def _daemonized_names(mod):
    """{key} of every ``<key>.daemon = True`` assignment — the
    standard ``t = Thread(...); t.daemon = True; t.start()`` idiom is
    just as shutdown-safe as the constructor keyword."""
    out = set()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            continue
        for t in node.targets:
            if not (isinstance(t, ast.Attribute)
                    and t.attr == "daemon"):
                continue
            key = engine.target_key(t.value)
            if key is not None:
                out.add(key)
    return out


@register("thread-lifecycle", "error",
          "started threads must be daemons or have a join path",
          scope="module")
def check_thread_lifecycle(project):
    findings = []
    for mod in project.modules:
        joined = None              # computed lazily per module
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if engine.call_name(node) != "Thread":
                continue
            # only the real constructor: threading.Thread (under any
            # import alias) / a bare imported Thread
            if isinstance(fn, ast.Attribute):
                base = fn.value
                if not isinstance(base, ast.Name):
                    continue
                if base.id != "threading" and mod.imports.get(
                        base.id) != ("module", "threading"):
                    continue
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = kw.value
            if daemon is not None and not (
                    isinstance(daemon, ast.Constant)
                    and daemon.value is False):
                continue           # daemon=True (or dynamic): fine
            # non-daemon at construction: the handle must be kept AND
            # either .daemon = True'd or .join()ed in this module
            handle = engine.assigned_name(mod, node)
            if handle is None:
                handle = _comprehension_target(mod, node)
            if handle is not None:
                if joined is None:
                    joined = _joined_names(mod) \
                        | _daemonized_names(mod)
                if handle in joined:
                    continue
            findings.append(Finding(
                mod.relpath, node.lineno, "thread-lifecycle", "error",
                "thread started without daemon=True and without a "
                "join() on its handle — interpreter shutdown can "
                "hang on it",
                "pass daemon=True, or keep the handle and join() it "
                "in the owner's close()/stop()"))
    return findings
