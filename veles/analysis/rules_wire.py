"""wire-schema: producers and consumers of one frame kind must agree.

The master/slave protocol is tuples-over-pickle: every frame is
``(kind, ...)`` with a string kind at element 0, produced by
``send_frame``/``send_obj``/handler returns and consumed by indexing,
slicing (``resp[:4]``) and tuple unpacking at the far end. Arity is
version-negotiated BY HAND — a 2-tuple hello marks a pre-codec peer,
``welcome`` grew from 3 to 5 elements across PRs 6/7, pre-ISSUE-6
clients unpack ``resp[:4]`` and ignore the trace element — so nothing
but discipline stops a producer from growing a tuple its consumers
crash on (or a consumer from reading an element no producer ships).

This rule extracts the schema from both sides and cross-checks them
project-wide:

* **producers** — tuple literals with a string-constant head that are
  (a) arguments of a send-shaped call (``send_frame``/``send_obj``/
  ``_roundtrip``/``rpc``, including tuples built by a lambda handed
  to ``rpc``) or (b) returned from a handler-convention function
  (``handle``/``_handle``/``on_frame``). Each records
  ``(direction, kind) -> {arity: site}`` — request frames (client →
  server) and response frames (handler replies) are separate
  namespaces, because ``("job", sid, lease)`` and ``("job", payload,
  job_id, epoch, trace)`` share a kind but not a schema.
* **consumers** — any variable that is kind-tested (``V[0] ==
  "job"``, ``kind = V[0]; kind == "job"``, the negated early-exit
  spellings) and is either a handler-convention parameter (request
  side) or assigned from a call (response side). Inside the
  established kind context, ``V[i]`` reads, ``a, b = V`` exact
  unpacks and ``a, b, c, d = V[:4]`` slice unpacks each demand an
  arity — UNLESS guarded: a dominating ``len(V)`` comparison
  (positive branch, early-exit negation, or conditional expression),
  an exact-arity check (``len(V) != 5: break``), or a
  ``try/except (ValueError, TypeError)`` around the unpack (the
  mixed-version skew handler) all make the access version-safe.

A finding fires when an UNGUARDED consumer demand cannot be met by
every producer of that (direction, kind): an exact unpack of N while
a producer ships M != N, or an index/slice read past the smallest
produced arity. Kinds with no known producer are skipped — the rule
only judges schemas it can see both sides of.
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register

#: calls whose tuple-literal argument is a frame leaving THIS side;
#: direction is which namespace the schema lands in
_REQUEST_SENDS = frozenset(("send_frame", "_roundtrip", "roundtrip",
                            "rpc"))
_RESPONSE_SENDS = frozenset(("send_obj",))

#: handler-convention function names: their returned tuples are
#: response frames, their non-self parameters are request frames
_HANDLER_NAMES = frozenset(("handle", "_handle", "on_frame"))

#: except types whose handler marks an unpack as skew-guarded (the
#: consumer explicitly survives an arity mismatch)
_SKEW_CATCHES = frozenset(("ValueError", "TypeError", "Exception",
                           "BaseException", ""))


def _frame_tuple(node):
    """(kind, arity) when ``node`` is a frame-shaped tuple literal —
    ``("job", a, b)`` — else None."""
    if isinstance(node, ast.Tuple) and node.elts \
            and isinstance(node.elts[0], ast.Constant) \
            and isinstance(node.elts[0].value, str):
        return node.elts[0].value, len(node.elts)
    return None


def _collect_producers(project):
    """{(direction, kind): {arity: (relpath, lineno)}} over the whole
    project."""
    out = {}

    def add(direction, kind, arity, mod, lineno):
        sites = out.setdefault((direction, kind), {})
        sites.setdefault(arity, (mod.relpath, lineno))

    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                name = engine.call_name(node)
                direction = ("request" if name in _REQUEST_SENDS
                             else "response"
                             if name in _RESPONSE_SENDS else None)
                if direction is None:
                    continue
                for arg in node.args:
                    got = _frame_tuple(arg)
                    if got is None and isinstance(arg, ast.Lambda):
                        # genetics-style ``rpc(lambda sid: ("task",
                        # sid))``: the lambda builds the frame
                        got = _frame_tuple(arg.body)
                    if got is not None:
                        add(direction, got[0], got[1], mod,
                            arg.lineno)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    and node.name in _HANDLER_NAMES:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) \
                            and sub.value is not None:
                        got = _frame_tuple(sub.value)
                        if got is not None:
                            add("response", got[0], got[1], mod,
                                sub.lineno)
    return out


# -- consumer-side dataflow ---------------------------------------------


def _aliases(func):
    """{alias_name: frame_var} for ``kind = V[0]`` assignments."""
    out = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Subscript) \
                and isinstance(node.value.value, ast.Name) \
                and isinstance(node.value.slice, ast.Constant) \
                and node.value.slice.value == 0:
            out[node.targets[0].id] = node.value.value.id
    return out


def _kind_tested_vars(func, aliases):
    """Names compared ``V[0] ==/!= "str"`` anywhere in ``func``
    (directly or through a ``kind = V[0]`` alias)."""
    out = set()
    for node in ast.walk(func):
        if not (isinstance(node, ast.Compare) and node.comparators
                and isinstance(node.comparators[0], ast.Constant)
                and isinstance(node.comparators[0].value, str)):
            continue
        left = node.left
        if isinstance(left, ast.Subscript) \
                and isinstance(left.value, ast.Name) \
                and isinstance(left.slice, ast.Constant) \
                and left.slice.value == 0:
            out.add(left.value.id)
        elif isinstance(left, ast.Name) and left.id in aliases:
            out.add(aliases[left.id])
    return out


def _assigned_from_call(func):
    """Names bound from a bare call result (``resp = recv(...)``)."""
    out = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _constraints(test, frame_vars, aliases):
    """(pos, neg): constraints guaranteed when ``test`` is true /
    false. Each is ``(var, op, value)`` with op in {"kind", "floor",
    "exact"}. And-tests stack positives, or-tests stack the negated
    side (the early-exit spelling ``if V[0] != "job" or len(V) < 4:
    raise``)."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        pos, neg = _constraints(test.operand, frame_vars, aliases)
        return neg, pos
    if isinstance(test, ast.BoolOp):
        pos, neg = [], []
        for value in test.values:
            p, n = _constraints(value, frame_vars, aliases)
            if isinstance(test.op, ast.And):
                pos.extend(p)
            else:
                neg.extend(n)
        return pos, neg
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and len(test.comparators) == 1):
        return [], []
    left, op, right = test.left, test.ops[0], test.comparators[0]
    # V[0] == "kind" / alias == "kind"
    if isinstance(right, ast.Constant) and isinstance(right.value, str):
        var = None
        if isinstance(left, ast.Subscript) \
                and isinstance(left.value, ast.Name) \
                and isinstance(left.slice, ast.Constant) \
                and left.slice.value == 0:
            var = left.value.id
        elif isinstance(left, ast.Name):
            var = aliases.get(left.id)
        if var in frame_vars:
            if isinstance(op, ast.Eq):
                return [(var, "kind", right.value)], []
            if isinstance(op, ast.NotEq):
                return [], [(var, "kind", right.value)]
        return [], []
    # len(V) <op> n
    if isinstance(left, ast.Call) and engine.call_name(left) == "len" \
            and len(left.args) == 1 \
            and isinstance(left.args[0], ast.Name) \
            and left.args[0].id in frame_vars \
            and isinstance(right, ast.Constant) \
            and isinstance(right.value, int):
        var, n = left.args[0].id, right.value
        if isinstance(op, ast.Gt):
            return [(var, "floor", n + 1)], []
        if isinstance(op, ast.GtE):
            return [(var, "floor", n)], []
        if isinstance(op, ast.Lt):
            return [], [(var, "floor", n)]
        if isinstance(op, ast.LtE):
            return [], [(var, "floor", n + 1)]
        if isinstance(op, ast.Eq):
            return [(var, "exact", n)], []
        if isinstance(op, ast.NotEq):
            return [], [(var, "exact", n)]
    return [], []


def _apply(env, constraints):
    """New env dict with ``constraints`` folded in."""
    out = {v: dict(st) for v, st in env.items()}
    for var, op, value in constraints:
        st = out.setdefault(var, {"kind": None, "floor": 0,
                                  "exact": None})
        if op == "kind":
            st["kind"] = value
        elif op == "floor":
            st["floor"] = max(st["floor"], value)
        elif op == "exact":
            st["exact"] = value
            st["floor"] = max(st["floor"], value)
    return out


def _terminates(body):
    """True when a statement suite always leaves the enclosing suite
    (the early-exit guard shape)."""
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


class _ConsumerScan:
    """One function's consumer walk: tracks per-frame-var (kind,
    floor, exact) through branches and records unguarded demands."""

    def __init__(self, mod, func, frame_vars, aliases, directions,
                 records):
        self.mod = mod
        self.frame_vars = frame_vars
        self.aliases = aliases
        self.directions = directions    # var -> "request"|"response"
        self.records = records
        self.unpack_guard = 0
        env = {v: {"kind": None, "floor": 0, "exact": None}
               for v in frame_vars}
        self.walk_suite(func.body, env)

    # -- recording -----------------------------------------------------

    def _demand_index(self, var, i, env, lineno):
        st = env.get(var)
        if st is None or st["kind"] is None or i == 0:
            return
        if i < st["floor"]:
            return
        if st["exact"] is not None and i < st["exact"]:
            return
        self.records.append(
            (self.mod, lineno, self.directions[var],
             (var, st["kind"]), "index", i, st["floor"]))

    # -- expressions ---------------------------------------------------

    def scan_expr(self, expr, env):
        if expr is None or isinstance(
                expr, (ast.Lambda, ast.FunctionDef,
                       ast.AsyncFunctionDef)):
            return
        if isinstance(expr, ast.IfExp):
            pos, neg = _constraints(expr.test, self.frame_vars,
                                    self.aliases)
            self.scan_expr(expr.test, env)
            self.scan_expr(expr.body, _apply(env, pos))
            self.scan_expr(expr.orelse, _apply(env, neg))
            return
        if isinstance(expr, ast.BoolOp) \
                and isinstance(expr.op, ast.And):
            cur = env
            for value in expr.values:
                self.scan_expr(value, cur)
                pos, _ = _constraints(value, self.frame_vars,
                                      self.aliases)
                if pos:
                    cur = _apply(cur, pos)
            return
        if isinstance(expr, ast.Subscript) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in self.frame_vars \
                and isinstance(expr.slice, ast.Constant) \
                and isinstance(expr.slice.value, int):
            self._demand_index(expr.value.id, expr.slice.value, env,
                               expr.lineno)
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self.scan_expr(child, env)

    # -- statements ----------------------------------------------------

    def _scan_unpack(self, stmt, env):
        """``a, b = V`` / ``a, b, c, d = V[:4]`` demands."""
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], (ast.Tuple, ast.List))):
            return False
        elts = stmt.targets[0].elts
        if any(isinstance(e, ast.Starred) for e in elts):
            return False
        value = stmt.value
        if isinstance(value, ast.Name) \
                and value.id in self.frame_vars:
            st = env.get(value.id)
            if st is None or st["kind"] is None:
                return True
            if st["exact"] == len(elts) or self.unpack_guard:
                return True
            self.records.append(
                (self.mod, stmt.lineno, self.directions[value.id],
                 (value.id, st["kind"]), "exact", len(elts),
                 st["floor"]))
            return True
        if isinstance(value, ast.Subscript) \
                and isinstance(value.value, ast.Name) \
                and value.value.id in self.frame_vars \
                and isinstance(value.slice, ast.Slice) \
                and value.slice.lower is None \
                and isinstance(value.slice.upper, ast.Constant) \
                and isinstance(value.slice.upper.value, int):
            var, n = value.value.id, value.slice.upper.value
            st = env.get(var)
            if st is None or st["kind"] is None:
                return True
            if st["floor"] >= n or self.unpack_guard:
                return True
            self.records.append(
                (self.mod, stmt.lineno, self.directions[var],
                 (var, st["kind"]), "slice", n, st["floor"]))
            return True
        return False

    def walk_suite(self, stmts, env):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                pos, neg = _constraints(stmt.test, self.frame_vars,
                                        self.aliases)
                self.scan_expr(stmt.test, env)
                self.walk_suite(stmt.body, _apply(env, pos))
                self.walk_suite(stmt.orelse, _apply(env, neg))
                if _terminates(stmt.body) and not stmt.orelse:
                    # the early-exit guard shape: its negation holds
                    # for the REST of this suite
                    for var, op, value in neg:
                        st = env.setdefault(
                            var, {"kind": None, "floor": 0,
                                  "exact": None})
                        if op == "kind":
                            st["kind"] = value
                        elif op == "floor":
                            st["floor"] = max(st["floor"], value)
                        elif op == "exact":
                            st["exact"] = value
                            st["floor"] = max(st["floor"], value)
                continue
            if isinstance(stmt, ast.Try):
                skew = any(engine.handler_names(h) & _SKEW_CATCHES
                           for h in stmt.handlers)
                self.unpack_guard += bool(skew)
                self.walk_suite(stmt.body, {v: dict(s)
                                            for v, s in env.items()})
                self.unpack_guard -= bool(skew)
                for h in stmt.handlers:
                    self.walk_suite(h.body, {v: dict(s)
                                             for v, s in env.items()})
                self.walk_suite(stmt.orelse, env)
                self.walk_suite(stmt.finalbody, env)
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                if isinstance(stmt, ast.While):
                    self.scan_expr(stmt.test, env)
                else:
                    self.scan_expr(stmt.iter, env)
                self.walk_suite(stmt.body, {v: dict(s)
                                            for v, s in env.items()})
                self.walk_suite(stmt.orelse, env)
                continue
            if self._scan_unpack(stmt, env):
                continue
            for kind, child in engine.iter_stmt_children(stmt):
                if kind == "stmt":
                    self.walk_suite([child], env)
                else:
                    self.scan_expr(child, env)


def _collect_consumers(project):
    """[(mod, lineno, direction, (var, kind), form, n, floor)] of
    unguarded consumer demands across the project; ``floor`` is the
    dominating len() lower bound at the site (shorter producer
    variants are unreachable there)."""
    records = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            aliases = _aliases(node)
            tested = _kind_tested_vars(node, aliases)
            if not tested:
                continue
            from_call = _assigned_from_call(node)
            params = set()
            if node.name in _HANDLER_NAMES:
                params = {a.arg for a in node.args.args
                          if a.arg != "self"}
            directions = {}
            for var in tested:
                if var in params:
                    directions[var] = "request"
                elif var in from_call:
                    directions[var] = "response"
            if not directions:
                continue
            _ConsumerScan(mod, node, set(directions), aliases,
                          directions, records)
    return records


@register("wire-schema", "error",
          "frame producers and consumers of one (direction, kind) "
          "must agree on arity — unguarded unpacks/index reads are "
          "checked against every tuple the other side ships")
def check_wire_schema(project):
    producers = _collect_producers(project)
    findings = []
    for mod, lineno, direction, (var, kind), form, n, floor \
            in _collect_consumers(project):
        all_sites = producers.get((direction, kind))
        if not all_sites:
            continue            # no visible producer: nothing to judge
        # a dominating len() floor already screens out shorter
        # producer variants — this consumer can only ever SEE frames
        # of at least ``floor`` elements, so judge it against those
        sites = {a: s for a, s in all_sites.items() if a >= floor}
        if not sites:
            continue            # every producer is guard-rejected
        min_arity = min(sites)
        if form == "index" and min_arity <= n:
            pfile, pline = sites[min_arity]
            findings.append(Finding(
                mod.relpath, lineno, "wire-schema", "error",
                "%s[%d] reads element %d of a %r %s frame, but the "
                "producer at %s:%d ships only a %d-tuple"
                % (var, n, n, kind, direction, pfile, pline,
                   min_arity),
                "guard the read with `if len(%s) > %d:` (mixed-"
                "version peers), or grow every producer of this "
                "frame kind" % (var, n)))
        elif form == "exact":
            for arity in sorted(sites):
                if arity != n:
                    pfile, pline = sites[arity]
                    findings.append(Finding(
                        mod.relpath, lineno, "wire-schema", "error",
                        "tuple-unpacking %d element(s) from a %r %s "
                        "frame, but the producer at %s:%d ships a "
                        "%d-tuple — this unpack raises ValueError "
                        "at runtime"
                        % (n, kind, direction, pfile, pline, arity),
                        "unpack through an arity guard (`%s[:%d]` "
                        "after a len check, or try/except "
                        "ValueError) so mixed-version peers "
                        "degrade instead of crash" % (var, n)))
                    break
        elif form == "slice" and min_arity < n:
            pfile, pline = sites[min_arity]
            findings.append(Finding(
                mod.relpath, lineno, "wire-schema", "error",
                "unpacking %s[:%d] needs a %d-element %r %s frame, "
                "but the producer at %s:%d ships only a %d-tuple"
                % (var, n, n, kind, direction, pfile, pline,
                   min_arity),
                "check `len(%s) >= %d` first, or ship the missing "
                "elements from every producer" % (var, n)))
    return sorted(findings)
