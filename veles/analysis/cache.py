"""zlint incremental analysis cache.

A warm full-tree lint should pay only for what changed. The unit of
reuse is a **(rule, content signature) -> findings** entry on disk;
the interesting part is what goes into the signature, because a stale
hit is a silently wrong lint verdict:

* every key is salted with a hash of the ANALYZER itself (every
  ``veles/analysis/*.py`` source) — editing a rule invalidates the
  whole cache, so a rule change can never serve findings computed by
  its previous self;

* **module-scope** rules (``register(..., scope="module")``): a
  module's findings depend only on the module plus its transitive
  project-internal imports and any module defining a class with the
  same simple name as one in that closure (the project's
  ``class_index`` merges hierarchies by simple name, so a same-named
  class anywhere can contribute attr/lock/base facts). The key is the
  sorted (relpath, content-hash) list over that closure — editing one
  module re-analyzes only the modules whose closure contains it, and
  adding/removing an import CHANGES the closure and therefore the
  key (import-graph invalidation falls out of the signature, no
  separate dependency journal to keep honest);

* **project-scope** rules (cross-module dataflow: wire schemas, lock
  cycles, the taint engine): the key is the signature of the whole
  module set — any edit re-runs them. They are the minority; the
  module-scope majority is what makes the warm run cheap.

Findings are stored POST-pragma-filter (the pragma map is part of the
module's content, so a pragma edit re-keys the module) as JSON under
``DIR/<rule>/<key>.json`` and rebuilt into :class:`Finding` objects on
a hit — a warm run's output is byte-identical to a cold run's.
Entries are written atomically (tmp + rename) so concurrent lints
sharing a cache directory can only ever race to the same content.
"""

import hashlib
import json
import os

from veles.analysis.core import Finding, Project, pragma_filtered

#: bump to orphan every existing entry on a format change
_FORMAT = 1

_analyzer_salt = None


def analyzer_salt():
    """Hash of every ``veles/analysis/*.py`` source + the cache
    format version: the part of every key that says WHICH analyzer
    computed the entry."""
    global _analyzer_salt
    if _analyzer_salt is None:
        h = hashlib.sha256(b"zlint-cache-format-%d" % _FORMAT)
        pkg = os.path.dirname(os.path.abspath(__file__))
        for name in sorted(os.listdir(pkg)):
            if not name.endswith(".py"):
                continue
            with open(os.path.join(pkg, name), "rb") as f:
                h.update(name.encode() + b"\0" + f.read() + b"\0")
        _analyzer_salt = h.hexdigest()
    return _analyzer_salt


def _module_hash(mod):
    return hashlib.sha256(mod.source.encode("utf-8")).hexdigest()


class AnalysisCache:
    """On-disk findings cache (``velescli lint --cache DIR``)."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        # per-project memos (a cache object usually serves one
        # invocation, but tests reuse them across projects)
        self._memo_project = None
        self._hashes = {}          # relpath -> content hash
        self._closures = {}        # relpath -> frozenset(relpaths)

    # -- signatures ----------------------------------------------------

    def _prepare(self, project):
        if self._memo_project is project:
            return
        self._memo_project = project
        self._hashes = {m.relpath: _module_hash(m)
                        for m in project.modules}
        self._closures = {}

    def _import_targets(self, project, mod):
        """Project modules ``mod`` imports (either import form; a
        ``from pkg import symbol`` contributes both ``pkg.symbol``
        and ``pkg`` when they resolve — the binding reads through
        the package __init__)."""
        out = set()
        for target in mod.imports.values():
            if target[0] == "module":
                hit = project.module_by_dotted(target[1])
                if hit is not None:
                    out.add(hit)
            else:
                _, pkg, name = target
                hit = project.module_by_dotted(
                    "%s.%s" % (pkg, name) if pkg else name)
                if hit is not None:
                    out.add(hit)
                hit = project.module_by_dotted(pkg)
                if hit is not None:
                    out.add(hit)
        return out

    def closure(self, project, mod):
        """The relpath set a module-scope rule's findings in ``mod``
        may depend on: transitive imports, plus every module defining
        a class sharing a simple name with a class defined or named
        as a base anywhere in the closure (fixpoint — adding a module
        adds its imports and class names too)."""
        self._prepare(project)
        got = self._closures.get(mod.relpath)
        if got is not None:
            return got
        by_relpath = {m.relpath: m for m in project.modules}
        members = {mod.relpath}
        queue = [mod]
        seen_names = set()
        while queue:
            cur = queue.pop()
            for hit in self._import_targets(project, cur):
                if hit.relpath in by_relpath \
                        and hit.relpath not in members:
                    members.add(hit.relpath)
                    queue.append(hit)
            names = set(cur.classes)
            for info in cur.classes.values():
                names.update(info.bases)
            for name in names - seen_names:
                for info in project.class_index.get(name, ()):
                    rel = info.module.relpath
                    if rel in by_relpath and rel not in members:
                        members.add(rel)
                        queue.append(info.module)
            seen_names |= names
        got = frozenset(members)
        self._closures[mod.relpath] = got
        return got

    def _key(self, rule_id, relpaths):
        h = hashlib.sha256()
        h.update(analyzer_salt().encode())
        h.update(rule_id.encode() + b"\0")
        for rel in sorted(relpaths):
            h.update(rel.encode() + b"\0"
                     + self._hashes[rel].encode() + b"\0")
        return h.hexdigest()

    # -- storage -------------------------------------------------------

    def _path(self, rule_id, key):
        return os.path.join(self.directory, rule_id, key + ".json")

    def _load(self, rule_id, key):
        try:
            with open(self._path(rule_id, key),
                      encoding="utf-8") as f:
                return [Finding(**d) for d in json.load(f)]
        except (OSError, ValueError, TypeError):
            # missing, torn, or from an incompatible hand edit: a
            # miss, never an error
            return None

    def _store(self, rule_id, key, findings):
        path = self._path(rule_id, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump([fi.as_dict() for fi in sorted(findings)], f)
        os.replace(tmp, path)

    # -- the analyze() hook --------------------------------------------

    def run_rule(self, project, rule_id, fn, scope):
        """Run ``rule_id`` over ``project`` reusing stored results;
        -> (findings, fresh_module_count, cached_module_count)."""
        self._prepare(project)
        if scope != "module":
            key = self._key(rule_id,
                            [m.relpath for m in project.modules])
            got = self._load(rule_id, key)
            if got is not None:
                return got, 0, len(project.modules)
            got = pragma_filtered(project, fn(project))
            self._store(rule_id, key, got)
            return got, len(project.modules), 0
        findings = []
        missing = []
        keys = {}
        for mod in project.modules:
            keys[mod.relpath] = key = self._key(
                rule_id, self.closure(project, mod))
            got = self._load(rule_id, key)
            if got is None:
                missing.append(mod)
            else:
                findings.extend(got)
        if missing:
            # one sub-project covering every missing module's closure
            # (module-scope findings only need that much context);
            # findings for closure members that are themselves cached
            # are recomputed here but the CACHED copies win — both
            # were produced under the same closure signature
            by_relpath = {m.relpath: m for m in project.modules}
            need = set()
            for mod in missing:
                need |= self.closure(project, mod)
            sub = Project([by_relpath[rel] for rel in sorted(need)])
            raw = pragma_filtered(sub, fn(sub))
            wanted = {m.relpath for m in missing}
            per_module = {rel: [] for rel in wanted}
            for fi in raw:
                if fi.file in wanted:
                    per_module[fi.file].append(fi)
            for rel, got in per_module.items():
                self._store(rule_id, keys[rel], got)
                findings.extend(got)
        return (sorted(findings), len(missing),
                len(project.modules) - len(missing))
