"""loop-exception-safety: no handler chain may raise into the loop.

Everything the reactor dispatches — ``on_frame``/``on_timer``
methods, ``call_soon``/``call_later``/``every``/``post`` targets —
runs on the ONE loop thread carrying every connection, probe and
timer in the process. The loop's dispatch wraps callbacks in a
catch-all so a raising handler cannot kill the process, but the
recovery is blunt: the connection is closed, the frame is dropped,
and the peer re-syncs — an exception that escapes a handler chain is
a dropped slave or a severed stream, not a stack trace on someone's
terminal. The discipline is therefore: every ``raise`` reachable
from a loop callback must be caught by a ``try`` SOMEWHERE on the
chain before it reaches the reactor.

This rule runs the shared forward-dataflow fixpoint
(:class:`veles.analysis.engine.ForwardDataflow`) over the
interprocedural call graph: the fact flowing caller→callee is the
set of exception names some frame on the chain is guaranteed to
catch. At each function the transfer walks the body tracking lexical
``try`` nesting (handler bodies and ``orelse`` are OUTSIDE their own
try's protection), records every explicit ``raise X`` whose type —
resolved through the project class hierarchy plus the builtin
exception tree, so ``raise StaleLease(...)`` knows it is a
``ConnectionError`` — is not covered, and propagates the enlarged
caught-set into every resolvable callee.

Exemptions: ``raise NotImplementedError`` (the abstract-stub
convention — a subclass is expected to override, and hitting the
stub IS the loudest correct outcome) and bare re-``raise`` (it can
only re-throw something an enclosing handler already caught).
"""

import ast

from veles.analysis import engine
from veles.analysis.core import Finding, register


def _raise_type(node):
    """Simple type name of an explicit ``raise`` statement, or
    None (bare re-raise / unresolvable expression)."""
    exc = node.exc
    if exc is None:
        return None
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Attribute):
        return exc.attr
    if isinstance(exc, ast.Name):
        return exc.id
    return None


class _RaiseFlow(engine.ForwardDataflow):
    """Fact = frozenset of exception names guaranteed caught by some
    frame of the chain reaching this function."""

    def __init__(self, project):
        super().__init__(project)
        #: (relpath, lineno) -> (exc_name, chain) — first chain wins
        self.uncaught = {}

    def entries(self):
        for mod, cls_node, func, where in engine.reactor_callbacks(
                self.project):
            cls = mod.classes.get(cls_node.name) \
                if cls_node is not None else None
            yield mod, cls, func, frozenset(), where

    def transfer(self, mod, cls, func, caught, chain):
        out = []

        def walk(stmts, caught):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.Try):
                    names = set()
                    for h in stmt.handlers:
                        names |= engine.handler_names(h)
                    walk(stmt.body, caught | frozenset(names))
                    for h in stmt.handlers:
                        walk(h.body, caught)
                    walk(stmt.orelse, caught)
                    walk(stmt.finalbody, caught)
                    continue
                if isinstance(stmt, ast.Raise):
                    name = _raise_type(stmt)
                    if name is not None \
                            and name != "NotImplementedError" \
                            and not engine.exception_covered(
                                name, caught, self.project):
                        key = (mod.relpath, stmt.lineno)
                        self.uncaught.setdefault(
                            key, (name, chain))
                    continue
                for kind, child in engine.iter_stmt_children(stmt):
                    if kind == "stmt":
                        walk([child], caught)
                    else:
                        for call in engine.iter_calls(child):
                            out.append((call, caught))

        walk(func.body, caught)
        return out


@register("loop-exception-safety", "error",
          "call chains reachable from reactor callbacks must not "
          "raise exception types no frame on the chain catches — an "
          "escaped raise severs the connection/timer on the shared "
          "loop")
def check_loop_exception_safety(project):
    flow = _RaiseFlow(project)
    flow.run()
    findings = []
    for (relpath, lineno), (name, chain) in sorted(
            flow.uncaught.items()):
        findings.append(Finding(
            relpath, lineno, "loop-exception-safety", "error",
            "%s raised here can reach the reactor loop uncaught "
            "(via %s) — the loop's blanket recovery closes the "
            "connection and drops the frame" % (name,
                                                " -> ".join(chain)),
            "catch it in the handler chain and reply with an error "
            "frame (or log and degrade); only raise across the "
            "loop boundary when severing the peer IS the intent"))
    return findings
