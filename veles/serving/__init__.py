"""veles.serving — batched online inference (SURVEY.md §2.6 gap).

The training side ends at ``export_inference`` (archive on disk) and
the snapshotter (checkpoints); the reference platform handed actual
serving to the separate libVeles C++ engine. This package is the
JAX-native serving half of the north star:

* :mod:`veles.serving.model`    — archive loader + pure forward
  interpreter over the SAME xp-generic math the training ops use;
* :mod:`veles.serving.registry` — named model/version registry with
  hot reload and checkpoint-refresh (local or HTTPSnapshotStore);
* :mod:`veles.serving.engine`   — per-(model, bucket) compiled
  forward cache (jax.jit, donated batch buffers, warmup);
* :mod:`veles.serving.batcher`  — dynamic micro-batching with
  power-of-two buckets, per-request deadlines, backpressure shedding;
* :mod:`veles.serving.decode`   — the generative decode plane
  (ISSUE 11): paged KV cache in preallocated bucketed slots,
  continuous batching (admission into the in-flight decode batch at
  step boundaries), per-token streaming callbacks;
* :mod:`veles.serving.frontend` — reactor-hosted HTTP/JSON frontend
  (``/v1/models``, ``/v1/predict``, streaming ``/v1/generate``,
  ``/healthz``, ``/metrics``) and the ``velescli.py serve`` entry
  point.
"""

from veles.serving.batcher import (             # noqa: F401
    DeadlineExceeded, MicroBatcher, QueueFull)
from veles.serving.decode import (              # noqa: F401
    ContinuousBatcher, DecodePlan, GenerativeEngine, KVPool)
from veles.serving.engine import InferenceEngine  # noqa: F401
from veles.serving.model import ArchiveModel      # noqa: F401
from veles.serving.registry import ModelRegistry  # noqa: F401
