"""Generative decode serving: paged KV cache + continuous batching.

The one-shot ``/v1/predict`` plane (engine.py/batcher.py) prices a
whole forward per request; an LM deployment lives in the DECODE loop
— one token per step per sequence, each step needing the sequence's
K/V history. This module is that plane, vLLM/Orca-style, sized to the
repo's compile-once stance:

* **Paged KV cache** (:class:`KVPool`) — every attention layer's K/V
  for up to ``n_slots`` concurrent sequences lives in ONE
  preallocated device buffer per layer, ``(n_slots, H, max_len, dh)``.
  A sequence is admitted by GRANTING a slot index, not by allocating:
  prefill writes the slot's whole K/V row, decode scatters one
  position per step, and a finished/dropped sequence just returns its
  index to the free list. ``veles_serving_forward_cache_bytes``
  accounting extends over the pool (``KVPool.nbytes``).

* **Compiled program cache** (:class:`GenerativeEngine`) — the decode
  twin of ``engine.py``'s per-(model, bucket) cache: one
  ``prefill_b{P}`` program per power-of-two PROMPT bucket (full causal
  forward over the padded prompt + first-token sample + KV write into
  the granted slot) and ONE ``decode_step`` program for the whole
  pool (every slot advances one position per call — the per-sequence
  position vector is the batch-joinable carry from
  ``znicz_tpu/generate.py``). Parameters are runtime arguments, so a
  hot reload keeps every compiled program.

* **Continuous batcher** (:class:`ContinuousBatcher`) — a decode loop
  generalizing the micro-batcher's deadline/shedding machinery to
  long-lived sequences: new requests are admitted into the IN-FLIGHT
  decode batch at step boundaries (prefill in the request's bucket,
  then the sequence joins the shared step), EOS/max-token/cancelled
  sequences free their slots mid-flight, queue admission is bounded
  (:class:`~veles.serving.batcher.QueueFull` -> HTTP 503) and expired
  queue entries never reach prefill. Tokens are pushed to a
  per-request callback as they decode — what the frontend streams as
  chunked HTTP.

The decode math is NOT re-derived here: prefill walks the archive's
unit specs through the SAME shared formulas the training units and
``model.py`` use (``dense_attention_core_fwd``, ``block_fwd``,
``FORWARD_OPS``), and the per-step attention update is
``generate.attn_decode``/``block_decode`` — one copy of the math
repo-wide, pinned by the decode-equals-offline-generate test.

Instruments (all labelled by model): ``veles_serving_decode_*``
counters/gauges, ``veles_serving_kv_pool_slots`` /
``veles_serving_kv_slots_in_use``,
``veles_serving_generated_tokens_total``,
``veles_serving_first_token_seconds``.
"""

import collections
import threading
import time

import numpy

from veles import telemetry
from veles.logger import Logger
from veles.serving import tenants
from veles.serving.batcher import (DeadlineExceeded, QueueFull,
                                   timeout_seconds)
from veles.serving.model import FORWARD_OPS

#: decoded-token attribution by resolved tenant (ISSUE 18; bounded —
#: values are tenant-resolver output only, zlint telemetry-hygiene)
_T_TOKENS = telemetry.LazyChild(
    lambda: telemetry.counter(
        "veles_serving_tenant_tokens_total",
        "Tokens decoded by resolved tenant", ("tenant",)))

#: unit types that are sequence-free at decode time — one token's
#: activations flow through the SAME forward formula model.py serves
_TOKEN_TYPES = frozenset({
    "layernorm", "token_dense", "token_dense_relu",
    "transformer_ffn", "moe_ffn", "activation_tanh",
    "activation_relu", "activation_str", "activation_sigmoid",
})

#: default per-request decode budget when the client sends none
DEFAULT_MAX_TOKENS = 16

#: decode-loop wedge threshold (seconds without a completed step
#: while sequences are active) before healthy() reports not-ready —
#: generous enough to cover a first-request XLA compile
WEDGE_AFTER_S = 60.0


class DecodePlan:
    """Ordered decode walk over an :class:`ArchiveModel`'s unit
    specs: ``steps`` is ``(kind, spec, cache_index)`` with kinds
    ``embed`` / ``attn`` / ``stack`` / ``token``; attention-bearing
    steps get KV cache indices. Raises :class:`ValueError` for
    archives that cannot generate (no leading embedding, non-causal
    attention, unsupported unit types)."""

    def __init__(self, steps, cache_specs, dim, vocab):
        self.steps = steps
        #: per-cache (heads, head_dim) — one entry per attention
        #: layer, stacks contribute one per inner layer
        self.cache_specs = cache_specs
        self.dim = dim
        self.vocab = vocab

    @property
    def n_caches(self):
        return len(self.cache_specs)

    @classmethod
    def from_archive(cls, model):
        specs = model.units
        if not specs or specs[0]["type"] != "embedding":
            raise ValueError(
                "not a generative archive: the first unit must be an "
                "embedding (got %s)"
                % (specs[0]["type"] if specs else "no units"))
        emb = specs[0]
        dim = int(emb["config"]["dim"])
        vocab = int(emb["config"]["vocab_size"])
        steps = [("embed", emb, None)]
        cache_specs = []
        for spec in specs[1:]:
            t = spec["type"]
            cfg = spec.get("config", {})
            if t == "attention":
                if not cfg.get("causal"):
                    raise ValueError(
                        "%s: generation needs causal attention"
                        % spec["name"])
                steps.append(("attn", spec, len(cache_specs)))
                cache_specs.append(
                    (int(cfg["heads"]), dim // int(cfg["heads"])))
            elif t == "transformer_stack":
                if not cfg.get("causal"):
                    raise ValueError(
                        "%s: generation needs causal attention"
                        % spec["name"])
                steps.append(("stack", spec, len(cache_specs)))
                heads = int(cfg["heads"])
                cache_specs.extend(
                    [(heads, dim // heads)] * int(cfg["layers"]))
            elif t == "dropout":
                continue            # identity at inference
            elif t in _TOKEN_TYPES:
                steps.append(("token", spec, None))
            else:
                raise ValueError(
                    "cannot decode through unit %s (type %r)"
                    % (spec.get("name"), t))
        return cls(steps, cache_specs, dim, vocab)

    @classmethod
    def probe(cls, model):
        """True iff the archive can generate (cheap spec walk)."""
        try:
            cls.from_archive(model)
            return True
        except ValueError:
            return False

    def positions_limit(self, params):
        """Longest sequence the exported positions table supports
        (None = no positional embedding, unbounded)."""
        tree = params.get(self.steps[0][1]["name"], {})
        pos = tree.get("positions")
        return None if pos is None else int(pos.shape[0])


class KVPool:
    """The paged KV cache: one preallocated (n_slots, H, max_len, dh)
    K and V buffer per attention layer. Slots are the admission
    currency — :meth:`grant` pops a free index (None when full),
    :meth:`release` returns it. The arrays themselves are swapped
    wholesale by the engine's jitted programs (prefill writes a slot
    row, decode_step scatters one position per active row); stale K/V
    in a released slot is harmless — the next grant's prefill
    overwrites the full row and the position mask hides the rest.

    NOT thread-safe by itself: the continuous batcher serializes
    grant/release under its own lock."""

    def __init__(self, cache_specs, n_slots, max_len):
        import jax.numpy as jnp
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.K = [jnp.zeros((self.n_slots, h, self.max_len, dh),
                            jnp.float32) for h, dh in cache_specs]
        self.V = [jnp.zeros((self.n_slots, h, self.max_len, dh),
                            jnp.float32) for h, dh in cache_specs]
        self._free = list(range(self.n_slots - 1, -1, -1))

    def grant(self):
        return self._free.pop() if self._free else None

    def release(self, slot):
        self._free.append(slot)

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def in_use(self):
        return self.n_slots - len(self._free)

    def nbytes(self):
        """Preallocated pool bytes (the forward-cache accounting
        extension: these pages exist whether or not any sequence
        occupies them)."""
        return sum(int(numpy.prod(a.shape)) * 4
                   for a in self.K) * 2


def _sample_tokens(logits, temp, key):
    """Per-row sampling with a PER-SEQUENCE temperature vector:
    ``temp[b] == 0`` rows take the argmax, others sample the softmax
    at their own temperature — one program serves a batch mixing
    greedy and sampled requests."""
    import jax
    import jax.numpy as jnp
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe = jnp.maximum(temp, jnp.float32(1e-6))
    sampled = jax.random.categorical(
        key, logits / safe[..., None], axis=-1).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


class GenerativeEngine(Logger):
    """Compiled prefill/decode executor + KV pool for ONE generative
    :class:`ArchiveModel`. All device work happens on the continuous
    batcher's decode thread; only :meth:`set_params` (hot reload) is
    called from elsewhere, and params swap atomically (one attribute
    store — in-flight sequences finish on whichever tree their next
    step reads, the same contract the predict engine has)."""

    def __init__(self, model, n_slots=8, max_len=256, donate=None,
                 name="decode-engine"):
        self.name = name
        self.plan = DecodePlan.from_archive(model)
        limit = self.plan.positions_limit(model.params)
        if limit is not None and limit < max_len:
            # the exported positions table bounds the horizon: past
            # it there is no position embedding to look up
            self.info("clamping max_len %d -> %d (exported positions "
                      "table)", max_len, limit)
            max_len = limit
        self.max_len = int(max_len)
        self.pool = KVPool(self.plan.cache_specs, n_slots,
                           self.max_len)
        if donate is None:
            # pool-buffer donation is an accelerator win; the CPU
            # donation path is a known use-after-free hazard in this
            # jaxlib (see StepCompiler) — never donate there
            from veles.serving.engine import InferenceEngine
            donate = InferenceEngine._on_accelerator()
        self.donate = bool(donate)
        self._compiled_prefill = {}   # prompt bucket -> jitted fn
        self._step_fn = None
        self.compile_seconds = {}
        self.set_params(model)
        import jax
        self._key = jax.random.PRNGKey(0)
        self._fold = 0

    def set_params(self, model):
        """(Re-)upload the model's params — the hot-reload path; every
        compiled program keeps working (params are arguments)."""
        import jax
        trees = [model.params.get(spec["name"], {})
                 for _, spec, _ in self.plan.steps]
        self._params = jax.device_put(trees)

    # -- bucket math ---------------------------------------------------

    def prompt_bucket(self, n):
        """Smallest power-of-two prompt bucket >= n (caps at
        max_len)."""
        if n > self.max_len:
            raise ValueError("prompt of %d exceeds max_len %d"
                             % (n, self.max_len))
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.max_len)

    @property
    def compiled_buckets(self):
        return sorted(self._compiled_prefill)

    # -- program builders ----------------------------------------------

    def _build_prefill(self, bucket):
        """One jitted program per prompt bucket: full causal forward
        over the padded prompt, first-token sample at the true last
        position, and the slot's K/V row written into the pool.
        Right-padding is sound under causal attention: pad positions
        can only influence positions AFTER the prompt, which decode
        overwrites (K/V scatter at pos) or masks (arange > pos)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from veles.znicz_tpu.ops.attention import (
            dense_attention_core_fwd)
        from veles.znicz_tpu.parallel.pipeline import block_fwd

        steps = self.plan.steps
        pad = self.max_len - bucket

        def split(t, heads):
            b, s, d = t.shape
            return t.reshape(b, s, heads, d // heads) \
                .transpose(0, 2, 1, 3)

        def merge(t):
            b, h, s, dh = t.shape
            return t.transpose(0, 2, 1, 3).reshape(b, s, h * dh)

        def prefill(ptrees, poolK, poolV, slot, ids, length, temp,
                    key):
            # quantized at-rest weights densify INSIDE the trace
            # (serving/quant.py): matmul-consumer trees dequantize
            # whole (the convert+scale fuses into the consumer), the
            # embedding gathers its 1-byte rows FIRST and dequantizes
            # only the slice — the consumer there is a gather, and
            # densifying the vocab table per dispatch would erase the
            # bandwidth saving
            from veles.serving.quant import dense_params, gather_rows
            emb, ptrees = ptrees[0], [
                dense_params(jnp, t) for t in ptrees[1:]]
            x = gather_rows(jnp, emb["weights"], ids)
            pos_table = emb.get("positions")
            if pos_table is not None:
                x = x + gather_rows(jnp, pos_table,
                                    slice(None, bucket))
            caches = [None] * self.plan.n_caches
            for (kind, spec, ci), p in zip(steps[1:], ptrees):
                cfg = spec.get("config", {})
                if kind == "attn":
                    heads = int(cfg["heads"])
                    d = x.shape[-1]
                    qkv = jnp.matmul(x, p["weights"])
                    if p.get("bias") is not None:
                        qkv = qkv + p["bias"]
                    q = split(qkv[..., :d], heads)
                    k = split(qkv[..., d:2 * d], heads)
                    v = split(qkv[..., 2 * d:], heads)
                    scale = numpy.float32(
                        1.0 / numpy.sqrt(d // heads))
                    _, ctx = dense_attention_core_fwd(
                        jnp, q, k, v, True, scale)
                    y = jnp.matmul(merge(ctx), p["weights_out"])
                    if p.get("bias_out") is not None:
                        y = y + p["bias_out"]
                    if cfg.get("residual"):
                        y = y + x
                    caches[ci] = (k, v)
                    x = y
                elif kind == "stack":
                    heads = int(cfg["heads"])
                    eps = float(cfg["eps"])
                    for l in range(int(cfg["layers"])):
                        lp = {k2: p[k2][l] for k2 in p}
                        x, cache = block_fwd(jnp, x, lp, heads, True,
                                             eps)
                        caches[ci + l] = (cache["k"], cache["v"])
                else:
                    x = FORWARD_OPS[spec["type"]](jnp, x, p, spec)
            logits = lax.dynamic_index_in_dim(x[0], length - 1, 0,
                                              keepdims=False)
            tok = _sample_tokens(logits[None], temp[None], key)[0]
            for ci, (k, v) in enumerate(caches):
                k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
                v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
                poolK[ci] = lax.dynamic_update_slice(
                    poolK[ci], k, (slot, 0, 0, 0))
                poolV[ci] = lax.dynamic_update_slice(
                    poolV[ci], v, (slot, 0, 0, 0))
            return tok, poolK, poolV

        donate = (1, 2) if self.donate else ()
        return jax.jit(prefill, donate_argnums=donate)

    def _build_step(self):
        """THE decode program: every pool slot advances one position.
        Inactive slots (pos 0, token 0) compute a wasted lane — the
        price of a single static-shape program — and their sampled
        output is simply ignored host-side."""
        import jax
        import jax.numpy as jnp
        from veles.znicz_tpu.generate import attn_decode, block_decode

        steps = self.plan.steps

        def step(ptrees, poolK, poolV, tokens, pos, temp, key):
            # see prefill: matmul trees densify whole, the embedding
            # gathers its 1-byte rows first
            from veles.serving.quant import dense_params, gather_rows
            emb, ptrees = ptrees[0], [
                dense_params(jnp, t) for t in ptrees[1:]]
            key, sub = jax.random.split(key)
            x = gather_rows(jnp, emb["weights"], tokens)[:, None, :]
            pos_table = emb.get("positions")
            if pos_table is not None:
                x = x + gather_rows(jnp, pos_table, pos)[:, None, :]
            for (kind, spec, ci), p in zip(steps[1:], ptrees):
                cfg = spec.get("config", {})
                if kind == "attn":
                    x, (poolK[ci], poolV[ci]) = attn_decode(
                        x, pos, (poolK[ci], poolV[ci]), p,
                        int(cfg["heads"]),
                        p.get("bias") is not None,
                        bool(cfg.get("residual")))
                elif kind == "stack":
                    heads = int(cfg["heads"])
                    eps = float(cfg["eps"])
                    for l in range(int(cfg["layers"])):
                        lp = {k2: p[k2][l] for k2 in p}
                        x, (poolK[ci + l], poolV[ci + l]) = \
                            block_decode(
                                x, pos, (poolK[ci + l],
                                         poolV[ci + l]),
                                lp, heads, eps)
                else:
                    x = FORWARD_OPS[spec["type"]](jnp, x, p, spec)
            nxt = _sample_tokens(x[:, 0, :], temp, sub)
            return nxt, poolK, poolV, key

        donate = (1, 2) if self.donate else ()
        return jax.jit(step, donate_argnums=donate)

    def _compiled(self, bucket):
        fn = self._compiled_prefill.get(bucket)
        if fn is None:
            t0 = time.perf_counter()
            fn = self._build_prefill(bucket)
            self._compiled_prefill[bucket] = fn
            self.compile_seconds[bucket] = time.perf_counter() - t0
        return fn

    def warmup(self, buckets=None):
        """Pre-build the prompt-bucket prefill ladder and the decode
        step program (jit wrappers; XLA still compiles lazily at the
        first call per shape — one warm generation makes it real);
        -> compile_seconds. Bench and tests call this so timed rows
        never pay a build."""
        from veles.serving.engine import bucket_sizes
        for b in buckets or bucket_sizes(self.max_len):
            self._compiled(int(b))
        if self._step_fn is None:
            t0 = time.perf_counter()
            self._step_fn = self._build_step()
            self.compile_seconds["step"] = time.perf_counter() - t0
        return dict(self.compile_seconds)

    # -- execution (decode thread only) --------------------------------

    def prefill_into(self, slot, prompt, temperature):
        """Run the prompt's bucket prefill, write the slot's K/V row,
        sample the first token; -> int token."""
        import jax
        import jax.numpy as jnp
        n = len(prompt)
        bucket = self.prompt_bucket(n)
        ids = numpy.zeros((1, bucket), numpy.int32)
        ids[0, :n] = prompt
        self._fold += 1
        sub = jax.random.fold_in(self._key, self._fold)
        t0 = time.perf_counter()
        fn = self._compiled(bucket)
        tok, self.pool.K, self.pool.V = fn(
            self._params, self.pool.K, self.pool.V,
            jnp.int32(slot), jnp.asarray(ids),
            jnp.int32(n), jnp.float32(temperature), sub)
        if telemetry.tracer.active:
            telemetry.tracer.add_complete(
                "serving.prefill", t0, time.perf_counter() - t0,
                bucket=bucket, slot=int(slot))
        return int(tok)

    def step(self, tokens, pos, temp):
        """One decode step over the WHOLE pool; arrays are (n_slots,)
        host vectors; -> (n_slots,) next tokens (host)."""
        import jax.numpy as jnp
        if self._step_fn is None:
            t0 = time.perf_counter()
            self._step_fn = self._build_step()
            self.compile_seconds["step"] = time.perf_counter() - t0
        nxt, self.pool.K, self.pool.V, self._key = self._step_fn(
            self._params, self.pool.K, self.pool.V,
            jnp.asarray(tokens), jnp.asarray(pos),
            jnp.asarray(temp), self._key)
        return numpy.asarray(nxt)


class GenRequest:
    """One generation: prompt in, tokens out (pushed to
    ``on_token`` as they decode, collected in :attr:`tokens`).
    Token/done callbacks may be attached AFTER submission
    (:meth:`set_on_token` replays the backlog under the emission
    lock, so no token is lost or duplicated)."""

    def __init__(self, prompt, max_tokens, temperature, eos,
                 deadline, trace=None, tenant=None):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.eos = eos
        self.deadline = deadline
        self.trace = trace
        #: resolved tenant (ISSUE 18) + virtual finish tag: KV slots
        #: are granted least-tag-first so one tenant's burst cannot
        #: monopolise the decode batch (see ContinuousBatcher)
        self.tenant = tenant
        self.vft = 0.0
        self.t_submit = time.perf_counter()
        self.t_first = None         # wall of the first decoded token
        self.tokens = []
        self.finish_reason = None
        self.error = None
        self.done = threading.Event()
        self.slot = None
        self.cancelled = None       # reason string once cancelled
        self._lock = threading.Lock()
        self._on_token = None
        self._on_done = None
        self._notify = None         # batcher wake hook

    # -- client side ---------------------------------------------------

    def cancel(self, reason="cancelled"):
        """Stop decoding this request at the next step boundary and
        free its KV slot (client disconnect, shutdown). Safe from any
        thread; a finished request is untouched."""
        with self._lock:
            if self.done.is_set() or self.cancelled is not None:
                return
            self.cancelled = str(reason)
            notify = self._notify
        if notify is not None:
            notify()

    def set_on_token(self, fn):
        """Attach the per-token callback; tokens already decoded are
        replayed first (in order, under the emission lock)."""
        with self._lock:
            for tok in self.tokens:
                fn(tok)
            self._on_token = fn

    def set_on_done(self, fn):
        with self._lock:
            if not self.done.is_set():
                self._on_done = fn
                return
        fn(self)

    def wait(self, timeout=None):
        """Block until done; -> the token list (raises the failure
        error if any)."""
        if not self.done.wait(timeout):
            raise DeadlineExceeded("generation still running after "
                                   "%.1fs" % (timeout or 0))
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    # -- decode-thread side --------------------------------------------

    def _emit(self, tok):
        with self._lock:
            if self.t_first is None:
                self.t_first = time.perf_counter()
            self.tokens.append(tok)
            cb = self._on_token
            if cb is not None:
                try:
                    cb(tok)
                except Exception:
                    # a consumer callback must never kill the SHARED
                    # decode loop (its other sequences are innocent)
                    pass

    def _finish(self, reason=None, error=None):
        with self._lock:
            self.finish_reason = reason
            self.error = error
            cb = self._on_done
            self._on_done = None
            self.done.set()
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass


class ContinuousBatcher(Logger):
    """The decode loop: admission at step boundaries, shared decode
    batch, mid-flight slot recycling, bounded queue. One worker
    thread owns every device dispatch; public methods only touch the
    queue/bookkeeping under the lock."""

    def __init__(self, engine, max_queue=64,
                 default_timeout_ms=30000.0, name="decode",
                 model=None):
        self.name = name
        self.model = model or name
        self.engine = engine
        self.max_queue = int(max_queue)
        self.default_timeout = float(default_timeout_ms) / 1000.0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._active = {}           # slot -> GenRequest
        # weighted-fair slot grants (ISSUE 18): virtual time + last
        # finish tag per tenant, cost = prompt + token budget over
        # the tenant's priority weight. FIFO-equivalent with one
        # tenant (or no tenant table installed).
        self._vtime = 0.0
        self._vfinish = {}
        self._running = True
        self.last_step = time.monotonic()
        n_slots = engine.pool.n_slots
        # host-side carry vectors for the whole pool (inactive slots
        # ride along at pos 0 / token 0 / temp 0)
        self._tokens = numpy.zeros(n_slots, numpy.int32)
        self._pos = numpy.zeros(n_slots, numpy.int32)
        self._temp = numpy.zeros(n_slots, numpy.float32)
        #: (wall, n_tokens) per completed step for the tokens/s view
        self._step_log = collections.deque(maxlen=4096)
        label = (self.model,)
        self._c_requests = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_serving_decode_requests_total",
                "Generation requests admitted to the decode queue",
                ("model",)).labels(*label))
        self._c_shed = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_serving_decode_shed_total",
                "Generation requests shed on a full decode queue "
                "(503)", ("model",)).labels(*label))
        self._c_expired = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_serving_decode_expired_total",
                "Generation requests expired before a KV slot grant "
                "(504)", ("model",)).labels(*label))
        self._c_tokens = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_serving_generated_tokens_total",
                "Tokens decoded across all sequences",
                ("model",)).labels(*label))
        self._c_steps = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_serving_decode_steps_total",
                "Shared decode steps executed (each advances every "
                "active sequence one token)", ("model",)).labels(
                    *label))
        self._c_finished = telemetry.LazyChild(
            lambda: telemetry.counter(
                "veles_serving_decode_finished_total",
                "Finished generations by reason",
                ("model", "reason")))
        self._g_queue = telemetry.LazyChild(
            lambda: telemetry.gauge(
                "veles_serving_decode_queue_depth",
                "Generation requests waiting for a KV slot",
                ("model",)).labels(*label))
        self._g_slots = telemetry.LazyChild(
            lambda: telemetry.gauge(
                "veles_serving_kv_slots_in_use",
                "KV pool slots occupied by in-flight sequences",
                ("model",)).labels(*label))
        self._g_pool = telemetry.LazyChild(
            lambda: telemetry.gauge(
                "veles_serving_kv_pool_slots",
                "Preallocated KV pool slots (decode batch width)",
                ("model",)).labels(*label))
        self._h_first = telemetry.LazyChild(
            lambda: telemetry.histogram(
                "veles_serving_first_token_seconds",
                "Submit -> first streamed token",
                ("model",)).labels(*label))
        self._g_pool.get().set(n_slots)
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="%s-worker" % name)
        self._thread.start()

    # -- client side ---------------------------------------------------

    def submit(self, prompt, max_tokens=None, temperature=0.0,
               eos=None, timeout_ms=None, trace=None, tenant=None):
        """Enqueue one generation; -> :class:`GenRequest`. Raises
        :class:`QueueFull` (admission backpressure) or
        :class:`ValueError` (prompt/budget outside the pool
        geometry). ``timeout_ms`` bounds the wait for a KV slot, not
        the decode itself (a granted sequence runs to completion).
        ``tenant`` (resolver output) keys the weighted-fair slot
        grants."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must have at least one token")
        try:
            max_tokens = (DEFAULT_MAX_TOKENS if max_tokens is None
                          else int(max_tokens))
        except OverflowError:
            # int(float('inf')): keep the client-fixable 400 contract
            raise ValueError("max_tokens must be a finite integer, "
                             "got %r" % (max_tokens,))
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if len(prompt) + max_tokens > self.engine.max_len:
            raise ValueError(
                "prompt %d + max_tokens %d exceeds the KV slot "
                "length %d" % (len(prompt), max_tokens,
                               self.engine.max_len))
        timeout = timeout_seconds(timeout_ms, self.default_timeout)
        req = GenRequest(prompt, max_tokens, float(temperature),
                         None if eos is None else int(eos),
                         time.monotonic() + timeout, trace=trace,
                         tenant=tenant)
        with self._lock:
            if not self._running:
                raise RuntimeError("decode batcher is closed")
            if len(self._queue) >= self.max_queue:
                self._c_shed.get().inc()
                raise QueueFull(
                    "decode queue full (%d waiting, max %d)"
                    % (len(self._queue), self.max_queue))
            self._c_requests.get().inc()
            # fair-share tag: a sequence's cost is its whole KV
            # claim (prompt + token budget) over the tenant's weight
            start = max(self._vtime, self._vfinish.get(tenant, 0.0))
            req.vft = start + (len(prompt) + max_tokens) \
                / tenants.weight(tenant)
            self._vfinish[tenant] = req.vft
            req._notify = self._notify
            self._queue.append(req)
            self._g_queue.get().set(len(self._queue))
            self._wake.notify()
        return req

    def generate(self, prompt, max_tokens=None, temperature=0.0,
                 eos=None, timeout_ms=None, wait_s=120.0):
        """submit + wait: -> the generated token list."""
        return self.submit(prompt, max_tokens=max_tokens,
                           temperature=temperature, eos=eos,
                           timeout_ms=timeout_ms).wait(wait_s)

    def _notify(self):
        with self._lock:
            self._wake.notify()

    # -- worker --------------------------------------------------------

    def _admit_locked(self):
        """Sweep the queue: expired/cancelled requests fail WITHOUT
        prefill (even while the pool is saturated — a dead entry must
        not pin the bounded queue and shed live traffic), live ones
        take free KV slots in least-virtual-finish-tag order (ISSUE
        18: weighted fairness across tenants — FIFO when every tag
        came from one tenant); the rest keep their arrival order; ->
        the requests to prefill. Lock held."""
        live = []
        now = time.monotonic()
        while self._queue:
            req = self._queue.popleft()
            if req.cancelled is not None:
                self._finish_locked(req, req.cancelled)
            elif req.deadline < now:
                self._c_expired.get().inc()
                req._finish(error=DeadlineExceeded(
                    "no KV slot before deadline"))
                self._count_finish("expired")
            else:
                live.append(req)
        admitted = []
        if live and self.engine.pool.free_slots:
            granted = set()
            for req in sorted(live, key=lambda r: (r.vft,
                                                   r.tenant or "")):
                if not self.engine.pool.free_slots:
                    break
                req.slot = self.engine.pool.grant()
                self._active[req.slot] = req
                self._vtime = max(self._vtime, req.vft)
                admitted.append(req)
                granted.add(id(req))
            if granted:
                live = [r for r in live if id(r) not in granted]
        self._queue.extend(live)    # arrival order preserved
        self._g_queue.get().set(len(self._queue))
        self._g_slots.get().set(self.engine.pool.in_use)
        return admitted

    def _count_finish(self, reason):
        self._c_finished.get().labels(self.model, reason).inc()

    def _finish_locked(self, req, reason, error=None):
        """Free the slot (if granted) and complete the request.
        Lock held (slot bookkeeping); the done callback fires after
        via GenRequest._finish's own lock."""
        if req.slot is not None:
            self._active.pop(req.slot, None)
            self.engine.pool.release(req.slot)
            self._temp[req.slot] = 0.0
            self._pos[req.slot] = 0
            self._tokens[req.slot] = 0
            req.slot = None
            self._g_slots.get().set(self.engine.pool.in_use)
        self._count_finish(reason if error is None else "error")
        req._finish(reason=reason, error=error)
        if telemetry.tracer.active:
            args = {"model": self.model, "tokens": len(req.tokens),
                    "reason": reason or "error"}
            if req.trace is not None:
                args.update(req.trace.child().span_args())
            telemetry.tracer.add_complete(
                "serving.decode", req.t_submit,
                time.perf_counter() - req.t_submit, **args)

    def _deliver(self, req, tok):
        """Emit one decoded token and decide whether the sequence is
        done; -> finish reason or None (keeps decoding)."""
        req._emit(tok)
        self._c_tokens.get().inc()
        if req.tenant is not None:
            _T_TOKENS.get().labels(req.tenant).inc()
        if req.cancelled is not None:
            return req.cancelled
        if req.eos is not None and tok == req.eos:
            return "eos"
        if len(req.tokens) >= req.max_tokens:
            return "length"
        return None

    def _worker(self):
        while True:
            with self._lock:
                while self._running and not self._queue \
                        and not self._active:
                    self._wake.wait()
                if not self._running:
                    self._drain_locked()
                    return
                admitted = self._admit_locked()
            for req in admitted:
                try:
                    tok = self.engine.prefill_into(
                        req.slot, req.prompt, req.temperature)
                except Exception as exc:
                    self.warning("prefill failed: %s: %s",
                                 type(exc).__name__, exc)
                    with self._lock:
                        self._finish_locked(req, None, error=exc)
                    continue
                self._h_first.get().observe(
                    time.perf_counter() - req.t_submit)
                reason = self._deliver(req, tok)
                if reason is not None:
                    with self._lock:
                        self._finish_locked(req, reason)
                    continue
                # the sequence joins the shared decode batch: its
                # first generated token is the next step's input at
                # position len(prompt)
                self._tokens[req.slot] = tok
                self._pos[req.slot] = len(req.prompt)
                self._temp[req.slot] = req.temperature
            with self._lock:
                active = dict(self._active)
            self.last_step = time.monotonic()
            if not active:
                continue
            try:
                nxt = self.engine.step(self._tokens, self._pos,
                                       self._temp)
            except Exception as exc:
                self.warning("decode step failed: %s: %s",
                             type(exc).__name__, exc)
                with self._lock:
                    for req in list(self._active.values()):
                        self._finish_locked(req, None, error=exc)
                continue
            self._c_steps.get().inc()
            self._step_log.append((time.monotonic(), len(active)))
            self.last_step = time.monotonic()
            for slot, req in active.items():
                tok = int(nxt[slot])
                self._pos[slot] += 1
                reason = self._deliver(req, tok)
                if reason is not None:
                    with self._lock:
                        self._finish_locked(req, reason)
                else:
                    self._tokens[slot] = tok

    def _drain_locked(self):
        closed = RuntimeError("decode batcher closed")
        while self._queue:
            self._finish_locked(self._queue.popleft(), None,
                                error=closed)
        for req in list(self._active.values()):
            self._finish_locked(req, None, error=closed)
        self._g_queue.get().set(0)

    # -- operational surface -------------------------------------------

    def healthy(self):
        """(ok, reason) for the ``serving:<port>:decode`` readiness
        check: the worker must be alive, and while sequences are
        active the loop must keep completing steps."""
        if not self._thread.is_alive():
            if self._running:
                return False, "decode worker dead"
            return True, None           # closed deliberately
        with self._lock:
            busy = bool(self._active or self._queue)
        if busy and time.monotonic() - self.last_step > WEDGE_AFTER_S:
            return False, ("decode loop wedged (%.0fs since last "
                           "step)" % (time.monotonic()
                                      - self.last_step))
        return True, None

    def metrics(self, rate_window=10.0):
        """JSON view for ``/metrics.json`` and ``velescli top``."""
        now = time.monotonic()
        with self._lock:
            queued = len(self._queue)
            in_use = self.engine.pool.in_use
            recent = sum(n for t, n in self._step_log
                         if t > now - rate_window)
        first = self._h_first.get()
        out = {
            "queue_depth": queued,
            "kv_slots_in_use": in_use,
            "kv_pool_slots": self.engine.pool.n_slots,
            "kv_pool_bytes": self.engine.pool.nbytes(),
            "max_len": self.engine.max_len,
            "requests_total": int(self._c_requests.get().value),
            "generated_tokens_total": int(
                self._c_tokens.get().value),
            "steps_total": int(self._c_steps.get().value),
            "tokens_per_sec": round(recent / rate_window, 2),
        }
        p50 = first.percentile(0.5)
        if p50 is not None:
            out["first_token_ms_p50"] = round(p50 * 1000, 3)
            out["first_token_ms_p99"] = round(
                first.percentile(0.99) * 1000, 3)
        return out

    def close(self):
        """Stop the worker; queued AND in-flight requests fail with
        a closed error (their slots are released)."""
        with self._lock:
            self._running = False
            self._wake.notify_all()
        self._thread.join(timeout=10)
        with self._lock:
            if self._thread.is_alive():
                return              # wedged in a step; daemon thread
            self._drain_locked()
