"""Per-(model, bucket) compiled forward cache.

The serving twin of ``xla_step.py``'s compile-once stance: instead of
dispatching units one by one, a model's whole forward chain is traced
ONCE per padded batch bucket into a single jitted program with a
donated batch buffer (the input batch is engine-built scratch, so XLA
may reuse it for the first layer's output). Buckets are powers of two
up to ``max_batch`` — the batcher pads every micro-batch up to the
next bucket, so a handful of programs serve every batch size and no
request ever waits on a fresh compile after :meth:`warmup`.

``backend="numpy"`` evaluates the same pure function with plain numpy
(the oracle path — zero compile cost, useful for tests and tiny
models); ``backend="jit"`` uses jax; ``"auto"`` picks jit when jax
imports.
"""

import threading
import time

from veles import telemetry


def bucket_sizes(max_batch):
    """The power-of-two bucket ladder: 1, 2, 4, ... max_batch."""
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b <<= 1
    out.append(max_batch)
    return out


class InferenceEngine:
    """Compiled forward executor for ONE :class:`ArchiveModel`.

    Thread-safe: the compile cache is lock-protected; execution itself
    is free-running (pure functions, no shared buffers)."""

    def __init__(self, model, backend="auto", max_batch=64,
                 donate=None, quantize="none"):
        if backend == "auto":
            try:
                import jax  # noqa: F401
                backend = "jit"
            except Exception:
                backend = "numpy"
        if backend not in ("numpy", "jit"):
            raise ValueError("backend must be auto|numpy|jit, got %r"
                             % (backend,))
        from veles.serving.quant import validate_mode
        validate_mode(quantize)
        #: at-rest weight quantization mode (serving/quant.py):
        #: set_model re-quantizes the model's params IN PLACE, so the
        #: host at-rest copy and the device upload both ride 1
        #: byte/element; apply() densifies at dispatch
        self.quantize = quantize
        self.backend = backend
        self.max_batch = int(max_batch)
        self._lock = threading.Lock()
        self._compiled = {}          # batch shape -> compiled program
        self._building = {}          # batch shape -> threading.Event
        self.compile_seconds = {}    # bucket -> trace+compile time
        self._model = None
        self._jit_apply = None
        self._device_params = None
        if donate is None:
            # donation is a TPU/GPU win; on CPU jax only warns
            donate = self._on_accelerator()
        self.donate = bool(donate)
        self.set_model(model)

    @staticmethod
    def _on_accelerator():
        try:
            import jax
            return jax.devices()[0].platform != "cpu"
        except Exception:
            return False

    # -- model swap (hot reload) ---------------------------------------

    def set_model(self, model, params_only=False):
        """Swap the served model. ``params_only=True`` (same
        architecture — caller checked ``signature()``) keeps every
        compiled program and just re-uploads the params; otherwise the
        compile cache is invalidated."""
        with self._lock:
            self._model = model
            if not params_only:
                self._compiled.clear()
                self.compile_seconds = {}
                self._jit_apply = None
            if self.quantize != "none":
                # at-rest swap: a checkpoint refresh writes f32 leaves
                # back into the tree; re-quantizing here keeps host
                # AND device at 1 byte/element (already-quantized
                # leaves pass through untouched). Compiled programs
                # stay valid — the quantized payload and its scale are
                # runtime pytree leaves, exactly like plain params.
                from veles.serving.quant import quantize_tree
                model.params = quantize_tree(model.params,
                                             self.quantize)
            if self.backend == "jit":
                import jax
                self._device_params = jax.device_put(model.params)
            else:
                self._device_params = model.params

    @property
    def model(self):
        return self._model

    # -- bucket math ---------------------------------------------------

    def bucket_for(self, n):
        """Smallest power-of-two bucket >= n (caps at max_batch)."""
        if n > self.max_batch:
            raise ValueError("batch %d exceeds max_batch %d"
                             % (n, self.max_batch))
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    # -- compilation ---------------------------------------------------

    def _compile(self, shape):
        """Compiled program for a padded batch of ``shape`` — keyed on
        the FULL shape, so archives without a recorded
        input_sample_shape (no-loader exports) still compile from the
        real request shape."""
        while True:
            with self._lock:
                fn = self._compiled.get(shape)
                if fn is not None:
                    return fn
                pending = self._building.get(shape)
                if pending is None:
                    # claim the build; concurrent first requests at
                    # the same shape WAIT instead of each paying a
                    # duplicate multi-second compile
                    self._building[shape] = threading.Event()
                    if self._jit_apply is None:
                        import functools
                        import jax
                        import jax.numpy as jnp
                        self._jit_apply = jax.jit(
                            functools.partial(self._model.apply, jnp),
                            donate_argnums=(1,) if self.donate
                            else ())
                    jit_apply = self._jit_apply
                    break
            pending.wait()
        import jax
        import numpy
        try:
            t0 = time.perf_counter()
            compiled = jit_apply.lower(
                self._device_params,
                jax.ShapeDtypeStruct(shape, numpy.float32)).compile()
            dt = time.perf_counter() - t0
            if telemetry.tracer.active:
                telemetry.tracer.add_complete(
                    "serving.compile", t0, dt, bucket=shape[0])
            with self._lock:
                # params are a runtime ARGUMENT of the compiled
                # program, so a params_only hot reload keeps this
                # cache valid
                self._compiled[shape] = compiled
                self.compile_seconds[shape[0]] = dt
            return compiled
        finally:
            with self._lock:
                self._building.pop(shape).set()

    def warmup(self, buckets=None):
        """Precompile the bucket ladder so first requests never pay a
        trace+compile; returns {bucket: seconds}."""
        if self.backend != "jit" \
                or self._model.input_sample_shape is None:
            return {}
        for b in buckets or bucket_sizes(self.max_batch):
            self._compile((int(b),) + self._model.input_sample_shape)
        return dict(self.compile_seconds)

    @property
    def compiled_buckets(self):
        with self._lock:
            return sorted(shape[0] for shape in self._compiled)

    # -- execution -----------------------------------------------------

    def predict(self, x):
        """Run the forward on (n, *sample) rows; pads up to the bucket
        and slices the pad rows back off. -> (outputs, bucket)."""
        import numpy
        x = numpy.ascontiguousarray(x, numpy.float32)
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            pad = numpy.repeat(x[-1:], bucket - n, axis=0)
            x = numpy.concatenate([x, pad], axis=0)
        if self.backend == "numpy":
            y = self._model.apply(numpy, self._device_params, x)
        else:
            y = numpy.asarray(self._compile(x.shape)(
                self._device_params, x))
        return y[:n], bucket
