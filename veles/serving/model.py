"""Archive loading + the pure forward interpreter.

``export_inference`` writes ``contents.json`` + ``*.npy`` (the C++
engine's input format, SURVEY.md §3.5). :class:`ArchiveModel` loads
that archive back in Python and evaluates it as a PURE function
``apply(xp, params, x)`` — generic over the array module exactly like
the training ops, so the numpy backend and the jitted engine share one
formula set (and the jitted form needs no re-derivation: ``jax.jit``
traces the same code with ``xp = jax.numpy``).

The per-type forward math is NOT re-invented here: every formula is
the module-level helper the training units already share with their
oracles (``dense_attention_core_fwd``, ``ln_fwd``, ``block_fwd``,
``route_tokens``/``experts_fwd``, ``conv_math.im2col/col2im``, the
activation table) — one copy of the math repo-wide, so serving can
never drift from training. Unknown unit types fail loudly, mirroring
the C++ ``UnitFactory`` contract.

Parameters live OUTSIDE the spec (a ``{unit_name: {key: array}}``
pytree) so the registry can hot-swap freshly trained weights — from a
re-exported archive or a snapshotter checkpoint — without touching
the compiled forward.
"""

import json
import os

import numpy

from veles.znicz_tpu.ops import activations as A
from veles.znicz_tpu.ops import conv_math as CM


def _act(xp, name, v):
    return A.ACTIVATIONS[name][0](xp, v)


def _split_heads(t, heads):
    b, s, d = t.shape
    return t.reshape(b, s, heads, d // heads).transpose(0, 2, 1, 3)


def _merge_heads(t):
    b, h, s, dh = t.shape
    return t.transpose(0, 2, 1, 3).reshape(b, s, h * dh)


# -- per-type forward functions: fn(xp, x, p, spec) -> y ----------------


def _dense(act):
    def fn(xp, x, p, spec):
        cfg = spec["config"]
        x2 = x.reshape(x.shape[0], -1)
        w = p["weights"]
        v = xp.matmul(x2, w.T if spec.get("weights_transposed") else w)
        if p.get("bias") is not None:
            v = v + p["bias"]
        sample = tuple(cfg.get("output_sample_shape")
                       or (cfg["neurons"],))
        return _act(xp, act, v).reshape((x.shape[0],) + sample)
    return fn


def _conv(act):
    def fn(xp, x, p, spec):
        cfg = spec["config"]
        cols = CM.im2col(xp, x, cfg["ky"], cfg["kx"],
                         tuple(cfg["sliding"]),
                         CM.normalize_padding(tuple(cfg["padding"])))
        v = xp.matmul(cols, p["weights"].T)
        if p.get("bias") is not None:
            v = v + p["bias"]
        return _act(xp, act, v)
    return fn


def _pool_patches(xp, x, cfg, pad_value):
    """Ceil-semantics window patches (B,oy,ox,ky*kx,C) — the
    PoolingBase edge geometry (partial bottom/right windows pool)."""
    ky, kx = cfg["ky"], cfg["kx"]
    sy, sx = cfg["sliding"]
    b, h, w, c = x.shape
    oy = -(-max(h - ky, 0) // sy) + 1
    ox = -(-max(w - kx, 0) // sx) + 1
    need_h = (oy - 1) * sy + ky
    need_w = (ox - 1) * sx + kx
    if need_h > h or need_w > w:
        x = xp.pad(x, ((0, 0), (0, need_h - h), (0, need_w - w),
                       (0, 0)), constant_values=pad_value)
    cols = CM.im2col(xp, x, ky, kx, (sy, sx), (0, 0, 0, 0))
    return cols.reshape(b, oy, ox, ky * kx, c)


def _max_pool(xp, x, p, spec):
    return xp.max(_pool_patches(xp, x, spec["config"], -numpy.inf),
                  axis=3)


def _avg_pool_counts(shape, ky, kx, sy, sx):
    """True (unpadded) window sizes per output position — a pure
    function of the geometry, memoized so each request pays ONE
    im2col, not two (and jit traces embed it as a constant)."""
    key = (shape, ky, kx, sy, sx)
    counts = _AVG_COUNTS.get(key)
    if counts is None:
        ones = numpy.ones((1,) + shape, numpy.float32)
        cfg = {"ky": ky, "kx": kx, "sliding": (sy, sx)}
        counts = numpy.maximum(
            _pool_patches(numpy, ones, cfg, 0.0).sum(axis=3), 1.0)
        _AVG_COUNTS[key] = counts
    return counts


_AVG_COUNTS = {}


def _avg_pool(xp, x, p, spec):
    cfg = spec["config"]
    patches = _pool_patches(xp, x, cfg, 0.0)
    sy, sx = cfg["sliding"]
    counts = _avg_pool_counts(tuple(x.shape[1:]), cfg["ky"],
                              cfg["kx"], sy, sx)
    return patches.sum(axis=3) / counts


def _lrn(xp, x, p, spec):
    cfg = spec["config"]
    d = cfg["k"] + cfg["alpha"] * CM.sliding_channel_sum(
        xp, x * x, cfg["n"])
    if cfg["beta"] == 0.75:       # the LRNormalizerForward rewrite
        return x * (1.0 / xp.sqrt(d * xp.sqrt(d)))
    return x * d ** (-cfg["beta"])


def _embedding(xp, x, p, spec):
    ids = x.astype(numpy.int32 if xp is numpy else "int32")
    y = p["weights"][ids]
    pos = p.get("positions")
    if pos is not None:
        s = ids.shape[1]
        if s > pos.shape[0]:
            raise ValueError(
                "%s: sequence %d longer than the exported positions "
                "table (%d)" % (spec["name"], s, pos.shape[0]))
        y = y + pos[:s]
    return y


def _layernorm(xp, x, p, spec):
    from veles.znicz_tpu.ops.layernorm import ln_fwd
    return ln_fwd(xp, x, p["weights"], p["bias"],
                  spec["config"]["eps"])


def _token_dense(act):
    def fn(xp, x, p, spec):
        v = xp.matmul(x, p["weights"])
        if p.get("bias") is not None:
            v = v + p["bias"]
        return _act(xp, act, v)
    return fn


def _ffn(xp, x, p, spec):
    cfg = spec["config"]
    h = _act(xp, "strict_relu",
             xp.matmul(x, p["weights"]) + p["bias"])
    y = xp.matmul(h, p["weights2"]) + p["bias2"]
    return y + x if cfg["residual"] else y


def _attention(xp, x, p, spec):
    from veles.znicz_tpu.ops.attention import dense_attention_core_fwd
    cfg = spec["config"]
    heads = cfg["heads"]
    d = x.shape[-1]
    qkv = xp.matmul(x, p["weights"])
    if p.get("bias") is not None:
        qkv = qkv + p["bias"]
    q = _split_heads(qkv[..., :d], heads)
    k = _split_heads(qkv[..., d:2 * d], heads)
    v = _split_heads(qkv[..., 2 * d:], heads)
    scale = numpy.float32(1.0 / numpy.sqrt(d // heads))
    _, ctx = dense_attention_core_fwd(xp, q, k, v, cfg["causal"],
                                      scale)
    y = xp.matmul(_merge_heads(ctx), p["weights_out"])
    if p.get("bias_out") is not None:
        y = y + p["bias_out"]
    return y + x if cfg["residual"] else y


def _moe_one(xp, x, p, cfg):
    """Top-1 MoE over ONE sample's tokens (T, D)."""
    from veles.znicz_tpu.ops.moe import experts_fwd, route_tokens
    xt = x.reshape(-1, x.shape[-1])
    cap = max(1, int(numpy.ceil(
        cfg["capacity_factor"] * xt.shape[0] / cfg["experts"])))
    _, _, gate, dispatch = route_tokens(xp, xt, p["router"],
                                        cfg["experts"], cap)
    xe = xp.einsum("tec,td->ecd", dispatch, xt)
    _, ye = experts_fwd(xp, xe, p["weights"], p["bias"],
                        p["weights2"], p["bias2"], "strict_relu",
                        xp.einsum)
    yt = xp.einsum("tec,ecd->td", dispatch * gate[:, None, None], ye)
    return yt.reshape(x.shape)


def _moe_ffn(xp, x, p, spec):
    # route PER SAMPLE, not over the coalesced micro-batch: expert
    # capacity and the rank-based token dropping must depend only on
    # the request's own tokens, never on co-batched traffic or the
    # engine's bucket pad rows (training flat-routes its minibatch,
    # but a serving answer has to be a function of its input alone)
    cfg = spec["config"]
    y = xp.concatenate([_moe_one(xp, x[i:i + 1], p, cfg)
                        for i in range(x.shape[0])], axis=0)
    return y + x if cfg["residual"] else y


def _transformer_stack(xp, x, p, spec):
    from veles.znicz_tpu.parallel.pipeline import block_fwd
    cfg = spec["config"]
    for i in range(cfg["layers"]):
        x, _ = block_fwd(xp, x, {k: v[i] for k, v in p.items()},
                         cfg["heads"], cfg["causal"], cfg["eps"])
    return x


def _deconv(xp, x, p, spec):
    cfg = spec["config"]
    b, oy, ox, k = x.shape
    cols = xp.matmul(x.reshape(-1, k), p["weights"])
    return CM.col2im(xp, cols.reshape(b, oy, ox, -1),
                     (b,) + tuple(cfg["out_shape"]),
                     cfg["ky"], cfg["kx"], tuple(cfg["sliding"]),
                     CM.normalize_padding(tuple(cfg["padding"])))


def _depooling(xp, x, p, spec):
    cfg = spec["config"]
    ky, kx = cfg["ky"], cfg["kx"]
    sy, sx = cfg["sliding"]
    b, oy, ox, c = x.shape
    kk = ky * kx
    patches = xp.broadcast_to(x[:, :, :, None, :] / float(kk),
                              (b, oy, ox, kk, c))
    need_h = sy * (oy - 1) + ky
    need_w = sx * (ox - 1) + kx
    full = CM.col2im(xp, patches.reshape(b, oy, ox, kk * c),
                     (b, need_h, need_w, c), ky, kx, (sy, sx),
                     (0, 0, 0, 0))
    h, w, _ = cfg["out_shape"]
    return full[:, :h, :w, :]


def _identity(xp, x, p, spec):
    return x


def _activation(act):
    def fn(xp, x, p, spec):
        return _act(xp, act, x)
    return fn


#: type name -> forward fn; keys mirror export_inference.ENGINE_TYPES
#: (and libveles/src/units.cc registrations) one to one
FORWARD_OPS = {
    "all2all": _dense("linear"),
    "all2all_tanh": _dense("tanh"),
    "all2all_relu": _dense("relu"),
    "all2all_str": _dense("strict_relu"),
    "all2all_sigmoid": _dense("sigmoid"),
    "softmax": _dense("softmax"),
    "conv": _conv("linear"),
    "conv_tanh": _conv("tanh"),
    "conv_relu": _conv("relu"),
    "conv_str": _conv("strict_relu"),
    "conv_sigmoid": _conv("sigmoid"),
    "max_pooling": _max_pool,
    "avg_pooling": _avg_pool,
    "norm": _lrn,
    "dropout": _identity,       # inverted dropout: inference identity
    "activation_tanh": _activation("tanh"),
    "activation_relu": _activation("relu"),
    "activation_str": _activation("strict_relu"),
    "activation_sigmoid": _activation("sigmoid"),
    "embedding": _embedding,
    "layernorm": _layernorm,
    "token_dense": _token_dense("linear"),
    "token_dense_relu": _token_dense("strict_relu"),
    "transformer_ffn": _ffn,
    "attention": _attention,
    "moe_ffn": _moe_ffn,
    "transformer_stack": _transformer_stack,
    "deconv": _deconv,
    "depooling": _depooling,
}

#: spec keys that are metadata, not .npy parameter references
_NON_PARAM_KEYS = frozenset({"type", "name", "config",
                             "weights_transposed"})


class ArchiveModel:
    """A loaded inference archive: ordered unit specs + a params
    pytree, evaluated by :meth:`apply`."""

    def __init__(self, workflow_name, input_sample_shape, units,
                 params):
        self.workflow_name = workflow_name
        self.input_sample_shape = (None if input_sample_shape is None
                                   else tuple(input_sample_shape))
        self.units = units          # list of spec dicts
        self.params = params        # {unit_name: {key: np.float32 arr}}
        #: MANIFEST excerpt of the checkpoint the params came from
        #: (wall_time / ingest_wall / verdict), {} for archive-only
        #: models — what the serving staleness gauges read
        self.checkpoint_meta = {}
        for spec in units:
            if spec["type"] not in FORWARD_OPS:
                raise ValueError(
                    "cannot serve unit %s: unknown type %r"
                    % (spec.get("name"), spec["type"]))

    @classmethod
    def from_dir(cls, path):
        """Load ``contents.json`` + every referenced .npy from an
        ``export_inference`` artifact directory."""
        doc_path = os.path.join(path, "contents.json")
        with open(doc_path) as f:
            doc = json.load(f)
        if doc.get("format") != 1:
            raise ValueError("%s: unsupported archive format %r"
                             % (doc_path, doc.get("format")))
        units, params = [], {}
        for spec in doc["units"]:
            tree = {}
            for key, value in spec.items():
                if key in _NON_PARAM_KEYS or value is None:
                    continue
                if isinstance(value, str) and value.endswith(".npy"):
                    tree[key] = numpy.ascontiguousarray(
                        numpy.load(os.path.join(path, value)),
                        numpy.float32)
            units.append(spec)
            if tree:
                params[spec["name"]] = tree
        return cls(doc.get("workflow"), doc.get("input_sample_shape"),
                   units, params)

    # -- evaluation ----------------------------------------------------

    def apply(self, xp, params, x):
        """Pure forward through every unit; ``x``: (B, *sample).
        Quantized at-rest weights (serving/quant.py) densify here, AT
        dispatch — inside the trace on the jit path, where XLA fuses
        the convert+scale into the consumer matmul."""
        from veles.serving.quant import dense_params
        for spec in self.units:
            x = FORWARD_OPS[spec["type"]](
                xp, x, dense_params(xp, params.get(spec["name"], {})),
                spec)
        return x

    def __call__(self, x):
        return self.apply(numpy, self.params,
                          numpy.asarray(x, numpy.float32))

    # -- structure identity (the compiled-cache key) -------------------

    def signature(self):
        """Hashable architecture identity: types, configs and param
        shapes. Two models with equal signatures can share compiled
        programs (only the param VALUES differ)."""
        def freeze(v):
            return tuple(v) if isinstance(v, list) else v
        return tuple(
            (spec["type"], spec["name"],
             tuple(sorted((k, freeze(v))
                          for k, v in spec["config"].items())),
             tuple(sorted(
                 (k, t.shape)
                 for k, t in self.params.get(spec["name"], {})
                 .items())))
            for spec in self.units)

    # -- checkpoint refresh --------------------------------------------

    def load_checkpoint(self, target):
        """Refresh params from a snapshotter checkpoint (local path or
        ``http(s)://`` URI via HTTPSnapshotStore). The checkpoint's
        ``params`` tree is keyed by unit name with the same attr keys
        the archive uses; unit names absent from this model are
        ignored (the checkpoint also carries GD units), shape
        mismatches fail loudly. A manifest stamped with model-health
        verdict ``diverged`` is REFUSED: the registry's refresh then
        degrades to the loaded version (counted) instead of serving a
        blown-up model."""
        from veles.snapshotter import (_count_diverged_skip,
                                       load_snapshot_meta)
        state, manifest = load_snapshot_meta(target)
        health_doc = (manifest or {}).get("model_health")
        if isinstance(health_doc, dict) \
                and health_doc.get("verdict") == "diverged":
            _count_diverged_skip()
            raise ValueError(
                "checkpoint %s refused: MANIFEST model-health verdict "
                "is 'diverged' (%s)" % (
                    target,
                    "; ".join(health_doc.get("reasons") or ()) or "?"))
        loaded = 0
        for uname, tree in state.get("params", {}).items():
            if uname not in self.params:
                continue
            for key, value in tree.items():
                if key not in self.params[uname]:
                    continue
                value = numpy.asarray(value, numpy.float32)
                have = self.params[uname][key]
                if value.shape != have.shape:
                    raise ValueError(
                        "checkpoint %s: %s.%s shape %s != archive %s"
                        % (target, uname, key, value.shape,
                           have.shape))
                self.params[uname][key] = value
                loaded += 1
        if not loaded:
            raise ValueError(
                "checkpoint %s shares no parameters with this model "
                "(unit names: %s)" % (target,
                                      sorted(self.params)))
        manifest = manifest or {}
        self.checkpoint_meta = {
            "wall_time": manifest.get("wall_time"),
            "ingest_wall": manifest.get("ingest_wall"),
            "verdict": (health_doc or {}).get("verdict")
            if isinstance(health_doc, dict) else None,
        }
        return loaded
