"""HTTP/JSON serving frontend + ``velescli serve``.

Same zero-dependency stack as ``web_status.py``: since ISSUE 9 the
listener lives on the process's SHARED selector reactor
(``veles/reactor.py``). Probe and metrics surfaces answer INLINE on
the loop — no thread per request — while each ``POST /v1/predict``
is handed to a worker thread that parks inside the micro-batcher
until its batch completes: the dynamic batching still happens BETWEEN
those threads, so concurrency on the socket side directly becomes
batch fill on the device side (threads exist only where a request
genuinely waits on the device).

Endpoints:

* ``GET  /v1/models``  — registry listing (name, version, shapes,
  compiled buckets, generative flag)
* ``POST /v1/predict`` — ``{"model": name, "inputs": [[...], ...],
  "timeout_ms": 250}`` -> ``{"outputs": [...], "version": n}``;
  503 when shed (queue full), 504 when the deadline expired
* ``POST /v1/generate`` — the decode plane (ISSUE 11):
  ``{"model": name, "prompt": [int, ...], "max_tokens": 32,
  "temperature": 0.0, "eos": id, "stream": true}``. With
  ``stream`` (the default) the response is ``Transfer-Encoding:
  chunked`` ndjson written token by token THROUGH the reactor loop
  as the continuous batcher decodes — one ``{"token": t}`` line per
  token, then a ``{"done": true, "tokens": [...], "finish_reason":
  ...}`` line; a client that disconnects (or stalls past the
  write-queue bound) frees its KV slot mid-flight and counts
  ``veles_serving_rejected_total{reason="disconnect"}``. With
  ``stream: false`` one JSON reply carries the full token list.
  503 + Retry-After when the decode queue is full, 400 when the
  prompt/budget exceeds the KV slot geometry or the model is not
  generative.
* ``GET  /healthz``      — liveness (cached, non-blocking probe)
* ``GET  /readyz``       — readiness: 200 only while the registry
  holds a warm model, no snapshot-store circuit breaker is open, the
  batcher is not shedding above threshold and no SLO burn-rate alert
  fires — 503 with a machine-readable reason list otherwise
  (``veles/health.py``; checks run on the monitor thread, the probe
  handler reads one cached attribute)
* ``GET  /metrics/history`` — the health monitor's time-series ring
  (``?window=SECS``): sampled latency percentiles, queue depth,
  counters — what ``velescli top`` and an autoscaler trend on
* ``GET  /metrics``      — Prometheus text exposition of the process
  telemetry registry (serving latency histograms, queue gauges, shed/
  expired counters — plus whatever else this process instruments)
* ``GET  /metrics.json`` — the original JSON view (queue depth,
  batch-fill ratio, p50/p99 latency, requests/s, per model), exact
  pre-registry key shape
* ``GET  /debug/trace``  — Perfetto JSON of the flight-recorder
  window (``?window=SECS``); ``GET /debug/events`` — recent
  structured events. Live postmortem surfaces (``velescli debug``).
* ``GET  /debug/critical_path`` — the flight-recorder window as a
  per-leg request-time breakdown (queue → execute;
  ``?window=SECS``); ``GET /debug/profile?seconds=N&hz=H`` — a live
  sampling-profiler capture (speedscope JSON; captured on a worker
  thread via ``request.defer``, ``velescli profile``). Both from
  ``veles/profiling.py``.

Tracing: ``POST /v1/predict`` honours an incoming W3C ``traceparent``
header (or mints a fresh context) and returns ``traceparent`` on the
response; the request's queue wait and batched execution are recorded
as spans of that trace (see ``batcher.py``).

``register_status(web_status)`` surfaces the same metrics in the
training dashboard (``web_status.py``) so one page shows both halves
of a train→serve deployment.
"""

import json
import threading
import time

import numpy

from veles import health, reactor, telemetry
from veles.logger import Logger
from veles.serving import tenants
from veles.serving.batcher import DeadlineExceeded, QueueFull

#: overload rejections by reason (satellite, ISSUE 8; tenant label
#: since ISSUE 18): "shed" = the micro-batcher's queue was full,
#: "not_ready" = readiness was false (no warm model / breaker open /
#: SLO firing), "disconnect" = a streaming /v1/generate client
#: dropped (or overflowed its write queue) mid-decode and its KV slot
#: was reclaimed (ISSUE 11), "quota" = the tenant's token bucket was
#: dry (429), "priority" = a best-effort tenant shed first while the
#: process was under pressure (503)
_REJECTED = telemetry.LazyChild(
    lambda: telemetry.counter(
        "veles_serving_rejected_total",
        "Requests rejected with 429/503 before any forward compute, "
        "by reason and tenant", ("reason", "tenant")))

#: tenant label used before any table is installed / outside HTTP —
#: keeps the label set bounded without a resolver in the loop
_NO_TENANT = tenants.DEFAULT_TENANT


def _count_rejected(reason, tenant):
    _REJECTED.get().labels(reason, tenant or _NO_TENANT).inc()


#: per-tenant request/latency attribution (ISSUE 18). Tenant values
#: are RESOLVER OUTPUT only (bounded; zlint telemetry-hygiene).
#: Latency is observed for ANSWERED (2xx) requests — goodput latency,
#: the series the per-tenant p99 burn-rate SLOs watch.
_T_REQUESTS = telemetry.LazyChild(
    lambda: telemetry.counter(
        "veles_serving_tenant_requests_total",
        "Serving requests by resolved tenant and route",
        ("tenant", "route")))
_T_LATENCY = telemetry.LazyChild(
    lambda: telemetry.histogram(
        "veles_serving_tenant_latency_seconds",
        "End-to-end answered-request latency by resolved tenant",
        ("tenant",)))

#: Retry-After (seconds) sent with 503s: shed queues drain within a
#: batching window; readiness usually needs a reload/recovery cycle
RETRY_AFTER_SHED = 1
RETRY_AFTER_NOT_READY = 5

#: batcher-shedding readiness threshold: the process reports NOT
#: ready when more than this fraction of recent submissions (between
#: two monitor ticks, with a minimum volume) was shed — a router can
#: then drain it instead of hammering a saturated queue
SHED_READY_RATIO = 0.9
SHED_READY_MIN = 16


class ServingFrontend(Logger):
    """HTTP face of a :class:`ModelRegistry`; port=0 picks a free
    one (see ``.port``)."""

    def __init__(self, registry, port=0, host="127.0.0.1"):
        self.name = "serving"
        self.registry = registry
        # bind first (check names carry the port), wire health, THEN
        # accept: the first request may arrive the instant the
        # acceptor registers, and the predict gate reads self._monitor
        self._server = reactor.HttpServer(host, port, self._route,
                                          name="serving-http",
                                          start=False)
        self.port = self._server.port
        self.host = host
        self._check_names = ()
        self._shed_seen = None
        self.register_health()
        self._server.start()
        self.info("serving on http://%s:%d/", host, self.port)

    # -- routing (reactor loop; inline routes must not block) ----------

    def _route(self, request):
        path = request.path
        if request.method == "POST":
            if path == "/v1/predict":
                # predict parks in the micro-batcher until its batch
                # completes — exactly the wait that must NOT happen
                # on the loop, so each predict gets a worker thread
                # (that thread-count IS the batch fill, as before)
                request.defer(self._serve_predict, request)
            elif path == "/v1/generate":
                # generate SUBMITS (non-blocking) and then streams
                # from decode-thread callbacks, but the first-use
                # decoder build and a non-streaming wait do block —
                # worker thread, replies posted back to the loop
                request.defer(self._serve_generate, request)
            elif (path.startswith("/v1/models/")
                    and path.endswith("/refresh")):
                # the rolling-refresh hook: store scan + checkpoint
                # load both block — worker thread
                request.defer(self._serve_refresh, request,
                              path[len("/v1/models/"):-len("/refresh")])
            else:
                request.reply_json(404, {"error": "not found"})
            return
        if path.startswith(("/healthz", "/readyz",
                            "/metrics/history")):
            # probe contract (zlint probe-purity): serve the
            # monitor's CACHED verdict — no locks, no registry
            # scans, no network, inline on the loop
            code, payload = health.health_endpoint(path)
            request.reply_json(code, payload)
        elif path.startswith("/metrics.json"):
            # the pre-registry JSON shape, now a view over the
            # telemetry registry
            request.reply_json(200, self.metrics())
        elif path.startswith("/metrics"):
            reg = telemetry.get_registry()
            request.reply(200, reg.render_prometheus().encode(),
                          reg.CONTENT_TYPE)
        elif path.startswith("/debug/profile"):
            # the capture blocks for the requested window (zlint
            # profiler-safety): worker thread, reply via call_soon
            request.defer(self._serve_profile, request)
        elif path.startswith("/debug/model"):
            # model-health plane (veles/model_health.py): the cached
            # snapshot incl. per-model serving drift gauges — one
            # attribute read, safe inline on the loop
            from veles import model_health
            request.reply_json(200, model_health.debug_model_doc())
        elif path.startswith("/debug/tenants"):
            # tenant table + live bucket levels (ISSUE 18): a short
            # lock around a dict walk, no I/O — loop-safe
            table = tenants.get_table()
            if table is None:
                request.reply_json(
                    404, {"error": "no tenant table (--tenants)"})
            else:
                request.reply_json(200, table.describe())
        elif path.startswith("/debug/"):
            payload = telemetry.debug_endpoint(path)
            if payload is None:
                request.reply_json(404, {"error": "not found"})
            else:
                request.reply_json(200, payload)
        elif path.startswith("/v1/models"):
            request.reply_json(200,
                               {"models": self.registry.describe()})
        else:
            request.reply_json(404, {"error": "not found"})

    def _serve_refresh(self, request, name):
        """Worker-thread half of ``POST /v1/models/<name>/refresh``
        (ISSUE 16): hot-load either the explicit checkpoint in the
        body (``{"checkpoint": ...}`` — what the router's rolling
        refresh sends after its own health gate) or the newest
        healthy one the refresh poll finds (``{"store": ...}``
        optionally naming where to scan)."""
        try:
            doc = json.loads(request.body) if request.body else {}
        except ValueError:
            request.reply_json(400, {"error": "bad json"})
            return
        try:
            entry = self.registry.get(name)
        except KeyError:
            request.reply_json(404, {"error": "no model %r" % name})
            return
        # the body names filesystem/store targets: admit only paths
        # inside the stores this entry was configured with server-side
        # (zlint untrusted-path) — the HTTP plane must not get to
        # point the registry at arbitrary directories
        try:
            checkpoint, store = self.registry.resolve_refresh_target(
                entry, checkpoint=doc.get("checkpoint"),
                store=doc.get("store"))
        except ValueError as exc:
            request.reply_json(400, {"error": str(exc)})
            return
        try:
            if checkpoint:
                entry = self.registry.load(
                    name, entry.source, checkpoint=checkpoint,
                    refresh_store=store)
                loaded = checkpoint
            else:
                loaded = self.registry.refresh_newest(
                    name, store_target=store)
                entry = self.registry.get(name)
        except (ValueError, OSError) as exc:
            request.reply_json(409, {"error": str(exc)})
            return
        request.reply_json(200, {
            "model": name, "version": entry.version,
            "loaded": loaded,
            "checkpoint_meta": dict(entry.model.checkpoint_meta)})

    def _serve_profile(self, request):
        from veles import profiling
        code, body, ctype = profiling.profile_endpoint(request.path)
        request.reply(code, body, ctype)

    @staticmethod
    def _reply_headers(code, reply, tp_header):
        """Response headers for one JSON reply: the traceparent echo
        always; on 429/503 also Retry-After — an overload/quota/
        readiness rejection tells the caller WHEN to come back
        instead of a generic failure."""
        if code in (429, 503):
            return tp_header + (
                ("Retry-After",
                 str(reply.get("retry_after_s", RETRY_AFTER_SHED))),)
        return tp_header

    @staticmethod
    def _tenant_of(request):
        """Resolve the request's ``x-veles-tenant`` header to a
        BOUNDED tenant name (known key, configured default, or the
        ``other`` fold). With no table installed every caller is the
        default tenant — raw header values never reach a label."""
        table = tenants.get_table()
        if table is None:
            return _NO_TENANT
        return table.resolve(request.headers.get("x-veles-tenant"))

    def _serve_predict(self, request):
        # join the caller's distributed trace, or root a new one:
        # either way the response names the context so the caller
        # can correlate
        trace = telemetry.TraceContext.from_traceparent(
            request.headers.get("traceparent"))
        if trace is None:
            trace = telemetry.TraceContext.new()
        tp_header = (("traceparent", trace.to_traceparent()),)
        try:
            doc = json.loads(request.body)
        except ValueError:
            # the 400 carries the echo too: callers correlate
            # failures by the same header as successes
            request.reply_json(400, {"error": "bad json"},
                               headers=tp_header)
            return
        code, reply = self.predict_request(
            doc, trace=trace, tenant=self._tenant_of(request))
        request.reply_json(code, reply,
                           headers=self._reply_headers(
                               code, reply, tp_header))

    # -- generative decode (ISSUE 11) ----------------------------------

    def _serve_generate(self, request):
        """Worker-thread half of ``POST /v1/generate``: validate +
        submit to the continuous batcher, then either stream tokens
        as chunked ndjson (written through the reactor loop by the
        decode thread's callbacks) or wait and answer once."""
        trace = telemetry.TraceContext.from_traceparent(
            request.headers.get("traceparent"))
        if trace is None:
            trace = telemetry.TraceContext.new()
        tp_header = (("traceparent", trace.to_traceparent()),)
        try:
            doc = json.loads(request.body)
        except ValueError:
            request.reply_json(400, {"error": "bad json"},
                               headers=tp_header)
            return
        stream_mode = bool(doc.get("stream", True)) \
            if isinstance(doc, dict) else True
        tenant = self._tenant_of(request)
        if not stream_mode:
            code, reply = self.generate_request(doc, trace=trace,
                                                tenant=tenant)
            request.reply_json(code, reply,
                               headers=self._reply_headers(
                                   code, reply, tp_header))
            return
        t0 = time.perf_counter()
        code, reply, handle, entry = self._submit_generate(
            doc, trace, tenant)
        if handle is None:
            request.reply_json(code, reply,
                               headers=self._reply_headers(
                                   code, reply, tp_header))
            return
        stream = request.begin_stream(
            200, "application/x-ndjson", headers=tp_header,
            on_close=lambda reason: self._generate_disconnect(
                handle, reason, tenant))
        stream.write(json.dumps(
            {"model": entry.name, "version": entry.version}) + "\n")

        def on_token(tok):
            stream.write(json.dumps({"token": int(tok)}) + "\n")

        def on_done(req):
            if req.error is not None:
                stream.write(json.dumps(
                    {"error": str(req.error)}) + "\n")
            else:
                stream.write(json.dumps(
                    {"done": True, "n": len(req.tokens),
                     "tokens": [int(t) for t in req.tokens],
                     "finish_reason": req.finish_reason}) + "\n")
                _T_LATENCY.get().labels(tenant or _NO_TENANT) \
                    .observe(time.perf_counter() - t0)
            stream.end()

        handle.set_on_token(on_token)
        handle.set_on_done(on_done)

    def _generate_disconnect(self, handle, reason, tenant=None):
        """The stream's connection died before the terminal chunk
        (client gone, or its bounded write queue overflowed): stop
        decoding and give the KV slot back. Runs on the reactor loop
        — flag flips and a counter only, nothing blocking."""
        if handle.done.is_set():
            return                   # raced a normal finish: no-op
        _count_rejected("disconnect", tenant)
        handle.cancel("disconnect")

    def _submit_generate(self, doc, trace, tenant=None):
        """Validate + submit one generation; -> (code, error_reply,
        handle|None, entry|None). Shared by the streaming and
        one-shot paths."""
        _T_REQUESTS.get().labels(tenant or _NO_TENANT,
                                 "generate").inc()
        blocked = self._admission_block((":shedding",), tenant)
        if blocked:
            return blocked[0], blocked[1], None, None
        try:
            name = doc["model"]
            prompt = doc["prompt"]
            if not isinstance(prompt, (list, tuple)):
                raise TypeError("prompt must be a list of token ids")
        except (KeyError, TypeError) as exc:
            return 400, {"error": "bad request: %s" % exc}, \
                None, None
        try:
            entry = self.registry.get(name)
            decoder = self.registry.decoder(name)
        except KeyError as exc:
            return 404, {"error": str(exc)}, None, None
        except ValueError as exc:
            # loaded, but not an LM archive — client-fixable
            return 400, {"error": str(exc)}, None, None
        try:
            handle = decoder.submit(
                prompt, max_tokens=doc.get("max_tokens"),
                temperature=float(doc.get("temperature", 0.0)),
                eos=doc.get("eos"),
                timeout_ms=doc.get("timeout_ms"), trace=trace,
                tenant=tenant)
        except QueueFull as exc:
            _count_rejected("shed", tenant)
            return 503, {"error": str(exc),
                         "retry_after_s": RETRY_AFTER_SHED}, \
                None, None
        except (ValueError, TypeError) as exc:
            return 400, {"error": str(exc)}, None, None
        return 200, None, handle, entry

    def generate_request(self, doc, trace=None, wait_s=120.0,
                         tenant=None):
        """One-shot (non-streaming) generate: -> (code, reply dict).
        Shared by the HTTP handler and tests (no socket needed)."""
        t0 = time.perf_counter()
        with telemetry.context(trace):
            code, reply, handle, entry = self._submit_generate(
                doc, trace, tenant)
            if handle is not None:
                try:
                    tokens = handle.wait(wait_s)
                    code, reply = 200, {
                        "model": entry.name,
                        "version": entry.version,
                        "tokens": [int(t) for t in tokens],
                        "n": len(tokens),
                        "finish_reason": handle.finish_reason}
                    _T_LATENCY.get().labels(tenant or _NO_TENANT) \
                        .observe(time.perf_counter() - t0)
                except DeadlineExceeded as exc:
                    # the client hears failure — the generation must
                    # not keep decoding into an answer nobody reads
                    # (its KV slot frees at the next step boundary)
                    handle.cancel("wait timeout")
                    code, reply = 504, {"error": str(exc)}
                except Exception as exc:
                    handle.cancel("request failed")
                    code, reply = 500, {"error": "%s: %s"
                                        % (type(exc).__name__, exc)}
        if telemetry.tracer.active:
            args = {"code": code, "model": str(doc.get("model"))
                    if isinstance(doc, dict) else "?"}
            if trace is not None:
                args.update(trace.span_args())
            telemetry.tracer.add_complete(
                "http.generate", t0, time.perf_counter() - t0,
                **args)
        return code, reply

    # -- readiness (veles/health.py) -----------------------------------

    def register_health(self, monitor=None):
        """Wire this frontend's readiness into the health monitor.
        The checks run on the MONITOR thread (they may take the
        registry lock and read breaker state); ``/readyz`` serves the
        cached verdict. Names carry the port so several frontends in
        one process (tests) keep distinct checks."""
        monitor = monitor or health.get_monitor()
        self._monitor = monitor
        prefix = "serving:%d" % self.port
        self._check_names = (prefix + ":models",
                             prefix + ":snapshot_store",
                             prefix + ":shedding",
                             prefix + ":decode")
        # one tick for the batch, not one per check
        monitor.add_check(self._check_names[0], self._check_models,
                          tick=False)
        monitor.add_check(self._check_names[1], self._check_stores,
                          tick=False)
        monitor.add_check(self._check_names[2], self._check_shedding,
                          tick=False)
        monitor.add_check(self._check_names[3], self._check_decode)
        return monitor

    def _check_models(self):
        """Ready iff the registry serves at least one model and no
        requested warmup is still compiling its bucket ladder."""
        names = self.registry.names()
        if not names:
            return False, "no models loaded"
        cold = [e.name for e in self._entries()
                if not getattr(e, "warm", True)]
        if cold:
            return False, "warmup in progress: %s" % ", ".join(cold)
        return True, None

    def _entries(self):
        out = []
        for name in self.registry.names():
            try:
                out.append(self.registry.get(name))
            except KeyError:       # unloaded between names() and get()
                continue
        return out

    def _check_stores(self):
        """Fail while any model's HTTP checkpoint store has its
        circuit breaker open (refreshes are fast-failing)."""
        broken = []
        for entry in self._entries():
            store = self.registry._checkpoint_store(entry.checkpoint)
            if store is not None and store.breaker_open():
                broken.append(entry.name)
        if broken:
            return False, ("snapshot-store breaker open for: %s"
                           % ", ".join(broken))
        return True, None

    def _check_shedding(self):
        """Fail while the micro-batcher shed more than
        :data:`SHED_READY_RATIO` of the submissions since the last
        tick (minimum :data:`SHED_READY_MIN` sheds — a lone 503 on an
        idle process must not flip readiness)."""
        reg = telemetry.get_registry()
        shed = reg.counter_total("veles_serving_shed_total")
        accepted = reg.counter_total("veles_serving_requests_total")
        prev = self._shed_seen
        self._shed_seen = (shed, accepted)
        if prev is None:
            return True, None
        d_shed = shed - prev[0]
        d_total = d_shed + max(accepted - prev[1], 0.0)
        if d_shed >= SHED_READY_MIN \
                and d_shed > SHED_READY_RATIO * d_total:
            return False, ("shedding %d/%d recent submissions"
                           % (int(d_shed), int(d_total)))
        return True, None

    def _check_decode(self):
        """Fail while any model's decode loop is dead or wedged
        (``ContinuousBatcher.healthy``): the worker thread must be
        alive and, with sequences in flight, keep completing steps.
        Models that never built a decoder (or aren't generative)
        don't participate."""
        bad = []
        for entry in self._entries():
            decoder = getattr(entry, "decoder", None)
            if decoder is not None:
                ok, why = decoder.healthy()
                if not ok:
                    bad.append("%s: %s" % (entry.name, why))
        if bad:
            return False, "; ".join(bad)
        return True, None

    # -- request handling ----------------------------------------------

    def predict_request(self, doc, trace=None, tenant=None):
        """-> (http_code, reply_dict); shared by the HTTP handler and
        tests (no socket needed to exercise the logic). ``trace`` is
        the request's :class:`veles.telemetry.TraceContext` — threaded
        through batcher and engine so queue wait and batched execution
        appear as spans of the caller's trace. ``tenant`` is resolver
        output (bounded; see :meth:`_tenant_of`)."""
        t0 = time.perf_counter()
        _T_REQUESTS.get().labels(tenant or _NO_TENANT,
                                 "predict").inc()
        # bind the request's trace as the thread's active context so
        # every log line emitted on its behalf carries the ids
        # (structured-log/trace correlation — veles/logger.py)
        with telemetry.context(trace):
            code, reply = self._predict_request(doc, trace, tenant)
        if code == 200:
            _T_LATENCY.get().labels(tenant or _NO_TENANT) \
                .observe(time.perf_counter() - t0)
        if telemetry.tracer.active:
            args = {"code": code, "model": str(doc.get("model"))
                    if isinstance(doc, dict) else "?"}
            if trace is not None:
                args.update(trace.span_args())
            telemetry.tracer.add_complete(
                "http.predict", t0, time.perf_counter() - t0, **args)
        return code, reply

    def _admission_block(self, exclude, tenant=None):
        """The (code, reply) that should reject this admission, or
        None. Three gates, in order:

        * **readiness** — a not-ready process (cold registry, open
          breaker, firing SLO) must shed load with an honest retry
          hint, not half-serve it — EXCEPT the ``exclude`` check
          suffixes: shedding-only unreadiness would flap at the
          monitor interval (no admissions -> next tick sees zero
          sheds -> ready -> readmit the storm), and a wedged DECODE
          loop must not refuse plain predicts. /readyz still reports
          everything, so a router can drain. Reasons are keyed on
          the check NAME part of "name: reason" (several frontends
          may share this process's monitor). 503.
        * **priority** (ISSUE 18) — while the shedding check fires,
          best-effort tenants (priority class ``batch``) are shed
          FIRST even though the check is excluded for everyone else:
          pressure relief starts with the traffic that asked to be
          preemptible. 503.
        * **quota** (ISSUE 18) — the tenant's token bucket; a dry
          bucket answers 429 with the exact Retry-After the bucket
          computes.

        Every rejection is counted
        ``veles_serving_rejected_total{reason,tenant}``."""
        ready, reasons = self._monitor.ready_state()
        if not ready:
            blocking = [r for r in reasons
                        if not r.split(": ", 1)[0].endswith(exclude)]
            if blocking:
                _count_rejected("not_ready", tenant)
                return 503, {"error": "not ready",
                             "reasons": blocking,
                             "retry_after_s": RETRY_AFTER_NOT_READY}
        table = tenants.get_table()
        if table is None or tenant is None:
            return None
        if not ready and table.best_effort(tenant) \
                and any(r.split(": ", 1)[0].endswith(":shedding")
                        for r in reasons):
            _count_rejected("priority", tenant)
            return 503, {"error": "shed: best-effort tenant %r "
                         "under pressure" % tenant,
                         "retry_after_s": RETRY_AFTER_SHED}
        ok, retry_after = table.admit(tenant)
        if not ok:
            _count_rejected("quota", tenant)
            return 429, {"error": "quota exceeded for tenant %r"
                         % tenant,
                         "retry_after_s": round(retry_after, 3)}
        return None

    def _predict_request(self, doc, trace, tenant=None):
        blocked = self._admission_block((":shedding", ":decode"),
                                        tenant)
        if blocked:
            return blocked
        try:
            name = doc["model"]
            inputs = numpy.asarray(doc["inputs"], numpy.float32)
        except (KeyError, TypeError, ValueError) as exc:
            return 400, {"error": "bad request: %s" % exc}
        try:
            entry = self.registry.get(name)
        except KeyError as exc:
            return 404, {"error": str(exc)}
        sample = entry.model.input_sample_shape
        if inputs.ndim > 0 and sample is not None \
                and inputs.shape[1:] != sample:
            # accept a single un-batched sample by promoting it
            if inputs.shape == sample:
                inputs = inputs[None]
            else:
                return 400, {"error": "input shape %s != (n,)+%s"
                             % (inputs.shape, sample)}
        elif sample is None and inputs.ndim == 1:
            # no recorded sample shape to validate against: a flat
            # list is one sample, not N scalar rows
            inputs = inputs[None]
        if inputs.ndim == 0 or inputs.shape[0] == 0:
            return 400, {"error": "empty inputs"}
        try:
            out = entry.predict(inputs,
                                timeout_ms=doc.get("timeout_ms"),
                                trace=trace, tenant=tenant)
        except QueueFull as exc:
            _count_rejected("shed", tenant)
            return 503, {"error": str(exc),
                         "retry_after_s": RETRY_AFTER_SHED}
        except DeadlineExceeded as exc:
            return 504, {"error": str(exc)}
        except (ValueError, TypeError) as exc:
            # client-fixable: too many rows for max_batch, garbage
            # timeout_ms — a 4xx, not a server fault
            return 400, {"error": str(exc)}
        except Exception as exc:
            return 500, {"error": "%s: %s"
                         % (type(exc).__name__, exc)}
        return 200, {"model": name, "version": entry.version,
                     "outputs": numpy.asarray(out).tolist()}

    def metrics(self):
        return {"models": self.registry.metrics()}

    # -- dashboard integration -----------------------------------------

    def register_status(self, web_status):
        """Surface serving metrics in the web-status dashboard."""
        front = self

        def provider():
            per_model = front.registry.metrics()
            agg_rps = round(sum(m["requests_per_sec"]
                                for m in per_model.values()), 2)
            return {
                "mode": "serving",
                "workflow": ",".join(sorted(per_model) or ["-"]),
                "epoch": "",
                "best_metric": "",
                "last_metrics": {
                    name: {"rps": m["requests_per_sec"],
                           "fill": m["batch_fill_ratio"],
                           "p99_ms": m.get("latency_ms_p99"),
                           "queue": m["queue_depth"],
                           "shed": m["shed_total"]}
                    for name, m in per_model.items()},
                "complete": "rps=%s" % agg_rps,
            }

        web_status.register("serving:%d" % self.port, provider)

    def close(self):
        for name in self._check_names:
            self._monitor.remove_check(name, tick=False)
        if self._check_names:
            self._monitor.tick()
        self._check_names = ()
        self._server.close()


# -- velescli serve -----------------------------------------------------


def build_serve_argparser():
    import argparse
    p = argparse.ArgumentParser(
        prog="velescli serve",
        description="Serve exported models over HTTP with dynamic "
                    "batching")
    p.add_argument("--model", action="append", required=True,
                   metavar="NAME=DIR",
                   help="model name = export_inference artifact "
                        "directory (repeatable)")
    p.add_argument("--checkpoint", action="append", default=[],
                   metavar="NAME=PATH",
                   help="refresh NAME's params from a snapshotter "
                        "checkpoint (local path or http(s):// URI)")
    p.add_argument("--port", type=int, default=8080,
                   help="HTTP port (0 = pick a free one)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--backend", default="auto",
                   choices=("auto", "jit", "numpy"),
                   help="forward executor: jax.jit compiled (device) "
                        "or plain numpy")
    p.add_argument("--max-batch", type=int, default=64,
                   help="largest padded batch bucket")
    p.add_argument("--max-queue", type=int, default=256,
                   help="pending-row cap before requests are shed "
                        "with 503")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="batching window from the oldest queued "
                        "request")
    p.add_argument("--timeout-ms", type=float, default=1000.0,
                   help="default per-request deadline")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip bucket-ladder precompilation")
    from veles.serving.quant import MODES
    p.add_argument("--quantize-weights", default="none",
                   choices=MODES,
                   help="store model weights quantized at rest "
                        "(host + device; dequantized at dispatch) — "
                        "~1 byte/element, halving "
                        "veles_serving_forward_cache_bytes per model")
    p.add_argument("--decode-slots", type=int, default=8,
                   help="KV pool slots = width of the shared "
                        "continuous decode batch (/v1/generate)")
    p.add_argument("--decode-max-len", type=int, default=256,
                   help="per-slot KV length: prompt + max_tokens "
                        "must fit (clamped to the exported "
                        "positions table)")
    p.add_argument("--refresh-every", type=float, default=None,
                   metavar="SECS",
                   help="poll each model's snapshot store this often "
                        "and hot-load the newest HEALTHY checkpoint "
                        "(diverged blobs are skipped and counted)")
    p.add_argument("--refresh-store", action="append", default=[],
                   metavar="NAME=TARGET",
                   help="snapshot store (dir or http base) the "
                        "refresh poll scans for NAME; defaults to "
                        "the store implied by --checkpoint")
    p.add_argument("--tenants", default=None, metavar="PATH",
                   help="per-tenant QoS config (JSON: tenant -> "
                        "rps/burst quota + priority class, default "
                        "tenant for unkeyed callers; see "
                        "veles/serving/tenants.py). Enables "
                        "x-veles-tenant resolution, 429 quotas, "
                        "weighted-fair batching and per-tenant p99 "
                        "SLO burn rates")
    p.add_argument("--slo-config", default=None, metavar="PATH",
                   help="JSON list of SLO objectives evaluated by "
                        "the in-process health monitor (burn-rate "
                        "alerts -> /readyz, /debug/events, "
                        "veles_slo_* gauges; see veles/health.py)")
    p.add_argument("--web-status", type=int, default=None,
                   metavar="PORT",
                   help="also serve the status dashboard on this "
                        "port (0 = pick a free one)")
    return p


def _parse_kv(pairs, what):
    out = {}
    for pair in pairs:
        name, sep, value = pair.partition("=")
        if not sep or not name or not value:
            raise SystemExit("%s %r: expected NAME=VALUE"
                             % (what, pair))
        out[name] = value
    return out


def serve_main(argv=None):
    """``velescli.py serve ...`` — build the registry, start the
    frontend, run until interrupted."""
    from veles.serving.registry import ModelRegistry
    args = build_serve_argparser().parse_args(argv)
    models = _parse_kv(args.model, "--model")
    checkpoints = _parse_kv(args.checkpoint, "--checkpoint")
    refresh_stores = _parse_kv(args.refresh_store, "--refresh-store")
    unknown = sorted((set(checkpoints) | set(refresh_stores))
                     - set(models))
    if unknown:
        raise SystemExit("--checkpoint/--refresh-store for unloaded "
                         "model(s): %s" % ", ".join(unknown))
    telemetry.tracer.set_process_name("serving")
    if args.tenants:
        table = tenants.set_table(
            tenants.TenantTable.from_file(args.tenants))
        n = len(table.install_slos(health.get_monitor()))
        print("tenant table: %d tenant(s), %d p99 SLO(s)"
              % (len(table.names()), n), flush=True)
    registry = ModelRegistry(
        backend=args.backend, max_batch=args.max_batch,
        max_queue=args.max_queue, max_wait_ms=args.max_wait_ms,
        default_timeout_ms=args.timeout_ms,
        decode_slots=args.decode_slots,
        decode_max_len=args.decode_max_len,
        quantize_weights=args.quantize_weights)
    front = None
    try:
        # inside the guard from the first load on: a bad --model
        # archive (or a failing warmup) must not strand the
        # registry's batcher threads behind the SystemExit
        for name, source in sorted(models.items()):
            registry.load(name, source,
                          checkpoint=checkpoints.get(name),
                          warmup=not args.no_warmup,
                          refresh_store=refresh_stores.get(name))
        front = ServingFrontend(registry, port=args.port,
                                host=args.host)
        if args.refresh_every:
            def refresh_poll():
                while not poll_stop.wait(args.refresh_every):
                    for name in sorted(models):
                        try:
                            registry.refresh_newest(name)
                        except ValueError:
                            pass    # no store configured for it
            poll_stop = threading.Event()
            threading.Thread(target=refresh_poll, daemon=True,
                             name="RefreshPoll").start()
        if args.slo_config:
            n = health.get_monitor().load_slo_file(args.slo_config)
            front.info("%d SLO objective(s) loaded from %s", n,
                       args.slo_config)
        if args.web_status is not None:
            from veles.web_status import WebStatus
            status = WebStatus(port=args.web_status, host=args.host)
            front.register_status(status)
        print(json.dumps({
            "serving": "http://%s:%d" % (front.host, front.port),
            "models": [{"name": d["name"], "version": d["version"],
                        "backend": d["backend"],
                        "compiled_buckets": d["compiled_buckets"]}
                       for d in registry.describe()],
        }), flush=True)
        try:
            threading.Event().wait()    # serve until ^C / SIGTERM
        except KeyboardInterrupt:
            pass
    finally:
        if front is not None:
            front.close()
        registry.close()
    return 0
