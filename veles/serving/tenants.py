"""Per-tenant identity, quotas and fair-share weights (ISSUE 18).

The serving plane answered every caller as one anonymous client;
this module gives it the three per-tenant primitives the QoS layer
needs, kept deliberately tiny and lock-cheap because the resolver
sits on the hot admission path of every request:

* **bounded identity** — :meth:`TenantTable.resolve` maps the
  ``x-veles-tenant`` header to a KNOWN tenant name, the configured
  default for unkeyed callers, or the fixed ``"other"`` bucket for
  unknown keys. Telemetry labels only ever see resolver output, so
  label cardinality is ``len(tenants) + 2`` no matter what the
  internet sends (the unbounded-cardinality foot-gun zlint's
  ``telemetry-hygiene`` rule now guards).
* **token-bucket quotas** — :meth:`TenantTable.admit` charges one
  request against the tenant's ``rps``/``burst`` budget and, when
  the bucket is dry, says how long until it isn't (the 429's
  ``Retry-After``).
* **priority weights** — :meth:`TenantTable.weight` turns the
  tenant's priority class into the weight the micro-batcher's and
  continuous batcher's weighted-fair (virtual-time) queues schedule
  by, and :meth:`TenantTable.best_effort` marks the classes that
  are shed FIRST under pressure (503 before any compute).

Config is one JSON document (``velescli serve --tenants FILE``)::

    {"default": "anon",
     "slo": {"p99_ms": 250.0, "target": 0.001},
     "tenants": {
         "acme":  {"rps": 50, "burst": 100, "priority": "gold"},
         "anon":  {"rps": 5,  "burst": 10,  "priority": "bronze"},
         "batch": {"rps": 20, "burst": 20,  "priority": "batch"}}}

Omitted ``rps`` means unmetered; ``priority`` defaults to
``silver``. The optional ``slo`` block templates one per-tenant p99
burn-rate objective per configured tenant
(:meth:`TenantTable.install_slos` -> ``health.add_slo``).

The table is installed process-wide (:func:`set_table`) so the
batchers can look weights up without threading a handle through
every constructor; with no table installed every tenant weighs 1 and
the virtual-time queues degenerate to the exact FIFO order shipped
before this PR.
"""

import json
import threading
import time

#: the resolver's two synthetic tenants: unkeyed callers land on the
#: (configurable) default, unknown keys fold into one bounded bucket
DEFAULT_TENANT = "anon"
OTHER_TENANT = "other"

#: priority class -> fair-share weight. "batch" is best-effort: it
#: also sheds FIRST (503) while the process is under pressure.
PRIORITY_WEIGHTS = {"gold": 4.0, "silver": 2.0, "bronze": 1.0,
                    "batch": 1.0}
BEST_EFFORT = frozenset(("batch",))

_DEFAULT_SLO_P99_MS = 250.0
_DEFAULT_SLO_TARGET = 0.001


class TenantQuota(object):
    """One tenant's token bucket + priority class."""

    __slots__ = ("name", "rps", "burst", "priority", "_tokens",
                 "_stamp")

    def __init__(self, name, rps=None, burst=None, priority="silver"):
        if priority not in PRIORITY_WEIGHTS:
            raise ValueError(
                "tenant %r: unknown priority %r (one of %s)"
                % (name, priority,
                   ", ".join(sorted(PRIORITY_WEIGHTS))))
        if rps is not None and rps <= 0:
            raise ValueError("tenant %r: rps must be > 0" % name)
        self.name = name
        self.rps = float(rps) if rps is not None else None
        self.burst = float(burst) if burst is not None else (
            self.rps if self.rps is not None else None)
        self.priority = priority
        self._tokens = self.burst
        self._stamp = time.monotonic()

    def admit(self, now, cost=1.0):
        """-> (admitted, retry_after_s). Caller holds the table
        lock."""
        if self.rps is None:
            return True, 0.0
        self._tokens = min(
            self.burst,
            self._tokens + (now - self._stamp) * self.rps)
        self._stamp = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True, 0.0
        return False, max((cost - self._tokens) / self.rps, 0.001)


class TenantTable(object):
    """The per-tenant config: resolver + quotas + weights + the
    cached ``/debug/tenants`` document."""

    def __init__(self, tenants=None, default=DEFAULT_TENANT,
                 slo=None):
        self._lock = threading.Lock()
        self.default = default
        self.slo = dict(slo or {})
        self._quotas = {}
        for name, spec in sorted((tenants or {}).items()):
            spec = dict(spec or {})
            self._quotas[name] = TenantQuota(
                name, rps=spec.pop("rps", None),
                burst=spec.pop("burst", None),
                priority=spec.pop("priority", "silver"))
            if spec:
                raise ValueError(
                    "tenant %r: unknown key(s) %s"
                    % (name, ", ".join(sorted(spec))))
        # the default tenant always exists (unmetered unless listed)
        if default not in self._quotas:
            self._quotas[default] = TenantQuota(default)
        # ... and so does the unknown-key fold bucket
        if OTHER_TENANT not in self._quotas:
            self._quotas[OTHER_TENANT] = TenantQuota(OTHER_TENANT,
                                                     priority="bronze")

    @classmethod
    def from_file(cls, path):
        with open(path) as fin:
            doc = json.load(fin)
        return cls.from_dict(doc)

    @classmethod
    def from_dict(cls, doc):
        if not isinstance(doc, dict):
            raise ValueError("tenant config must be a JSON object")
        unknown = set(doc) - {"tenants", "default", "slo"}
        if unknown:
            raise ValueError("tenant config: unknown key(s) %s"
                             % ", ".join(sorted(unknown)))
        return cls(tenants=doc.get("tenants"),
                   default=doc.get("default", DEFAULT_TENANT),
                   slo=doc.get("slo"))

    # -- identity ------------------------------------------------------

    def resolve(self, key):
        """Bounded tenant name for one raw header value: the header's
        tenant if configured, the default for missing/empty keys, the
        ``other`` fold for everything else. THE only function whose
        output may reach a telemetry label."""
        if not key:
            return self.default
        return key if key in self._quotas else OTHER_TENANT

    def names(self):
        return sorted(self._quotas)

    # -- enforcement ---------------------------------------------------

    def admit(self, tenant, cost=1.0):
        """Charge ``cost`` requests against ``tenant``'s bucket ->
        (admitted, retry_after_s). Unknown tenants (resolver output
        only, so: the fold bucket) share ``other``'s bucket."""
        quota = self._quotas.get(tenant)
        if quota is None:
            quota = self._quotas[OTHER_TENANT]
        with self._lock:
            return quota.admit(time.monotonic(), cost)

    def weight(self, tenant):
        quota = self._quotas.get(tenant)
        if quota is None:
            return PRIORITY_WEIGHTS["bronze"]
        return PRIORITY_WEIGHTS[quota.priority]

    def best_effort(self, tenant):
        """True for tenants that shed FIRST while the process is
        under pressure (priority class ``batch``)."""
        quota = self._quotas.get(tenant)
        return quota is not None and quota.priority in BEST_EFFORT

    # -- observability -------------------------------------------------

    def describe(self):
        """The ``/debug/tenants`` document — config + live bucket
        levels. Cheap enough for the reactor loop: one small lock
        around a dict walk, no I/O."""
        now = time.monotonic()
        out = {}
        with self._lock:
            for name, q in sorted(self._quotas.items()):
                tokens = None
                if q.rps is not None:
                    tokens = min(q.burst, q._tokens
                                 + (now - q._stamp) * q.rps)
                out[name] = {
                    "priority": q.priority,
                    "weight": PRIORITY_WEIGHTS[q.priority],
                    "rps": q.rps, "burst": q.burst,
                    "tokens": (round(tokens, 3)
                               if tokens is not None else None),
                    "default": name == self.default}
        return {"default": self.default, "slo": self.slo,
                "tenants": out}

    def install_slos(self, monitor, series_tmpl=None):
        """One per-tenant p99 burn-rate objective per configured
        tenant (``health.add_slo`` "threshold" kind over the
        tenant-labelled serving latency histogram). -> names added."""
        p99_ms = float(self.slo.get("p99_ms", _DEFAULT_SLO_P99_MS))
        target = float(self.slo.get("target", _DEFAULT_SLO_TARGET))
        tmpl = series_tmpl or \
            'veles_serving_tenant_latency_seconds{tenant="%s"}:p99'
        names = []
        for tenant in self.names():
            name = "tenant_p99:%s" % tenant
            monitor.add_slo({
                "name": name, "kind": "threshold",
                "series": tmpl % tenant, "op": "<",
                "threshold": p99_ms / 1000.0, "target": target})
            names.append(name)
        return names


# -- the process-wide table ---------------------------------------------

_table = None
_table_lock = threading.Lock()


def set_table(table):
    """Install ``table`` process-wide (None uninstalls). The batchers
    read it for fair-share weights; the frontend for everything."""
    global _table
    with _table_lock:
        _table = table
    return table


def get_table():
    return _table


def weight(tenant):
    """Fair-share weight for ``tenant`` under the installed table
    (1.0 with no table — FIFO-equivalent scheduling)."""
    table = _table
    if table is None or tenant is None:
        return 1.0
    return table.weight(tenant)
