"""Dynamic micro-batching with deadlines and backpressure.

Concurrent requests (one sample or a few rows each) coalesce into one
forward per dispatch: the worker drains whatever is queued — up to
``max_batch`` rows — waiting at most ``max_wait_ms`` from the moment
the oldest request arrived, so a lone request still answers promptly
while a burst fills the batch (classic dynamic batching; the engine
pads the result up to its power-of-two bucket).

Overload policy, in order:

* **shedding** — :meth:`submit` raises :class:`QueueFull` once
  ``max_queue`` rows are pending; the frontend maps it to HTTP 503.
  Bounded queues instead of unbounded latency: under sustained
  overload every queued request would miss its deadline anyway.
* **deadlines** — each request carries an absolute deadline; requests
  already expired at dequeue time get :class:`DeadlineExceeded`
  (HTTP 504) WITHOUT wasting forward compute on them.

Metrics (queue depth, batch fill, latency percentiles, rps) are
collected here — the one place every request passes through. Since the
unified telemetry core (ISSUE 3) they live in the process-wide
registry (``veles.telemetry``) as ``veles_serving_*`` counters /
histograms labelled by model, and :meth:`MicroBatcher.metrics` is a
JSON *view* over those instruments with the exact pre-registry key
shape (served on ``/metrics.json``; the Prometheus scrape is
``/metrics``).
"""

import collections
import math
import threading
import time

from veles import telemetry
from veles.logger import Logger
from veles.serving import tenants


class QueueFull(Exception):
    """Backpressure: the pending queue is at capacity — shed."""


class DeadlineExceeded(Exception):
    """The request expired before a batch slot reached it."""


def timeout_seconds(timeout_ms, default_s):
    """Admit a client-supplied ``timeout_ms`` -> seconds. JSON can
    carry bare ``NaN``/``Infinity`` (Python's parser accepts them) and
    either would mint a deadline that never compares expired — the
    request then pins its queue slot forever while live traffic gets
    shed. Raises :class:`ValueError` (-> HTTP 400) for anything but a
    finite non-negative number."""
    if timeout_ms is None:
        return default_s
    try:
        t = float(timeout_ms)
    except (TypeError, ValueError):
        raise ValueError("timeout_ms must be a number, got %r"
                         % (timeout_ms,))
    if not math.isfinite(t) or t < 0:
        raise ValueError("timeout_ms must be finite and >= 0, got %r"
                         % (timeout_ms,))
    return t / 1000.0


class _Request:
    __slots__ = ("rows", "deadline", "t_enqueue", "t_perf", "event",
                 "result", "error", "trace", "tenant", "vft")

    def __init__(self, rows, deadline, trace=None, tenant=None):
        self.rows = rows
        self.deadline = deadline
        self.t_enqueue = time.monotonic()
        # tracer timestamps are perf_counter-based; monotonic is not
        # guaranteed to share its epoch, so keep a second reading
        self.t_perf = time.perf_counter()
        self.event = threading.Event()
        self.result = None
        self.error = None
        #: veles.telemetry.TraceContext of the originating request
        self.trace = trace
        #: resolved tenant (ISSUE 18) — the weighted-fair queue key
        self.tenant = tenant
        #: virtual finish tag (rows / tenant weight past the queue's
        #: virtual time at enqueue) — dequeue order under fairness
        self.vft = 0.0


class MicroBatcher(Logger):
    """Coalesces concurrent :meth:`submit` calls into batched
    ``run_batch(rows) -> (outputs, bucket)`` dispatches."""

    #: (metrics-view key, registry counter suffix, help) — the one
    #: table both the instrument creation and the JSON view read, so
    #: the /metrics.json key shape can never drift from the registry
    COUNTERS = (
        ("requests_total", "requests", "Requests submitted"),
        ("shed_total", "shed", "Requests shed on a full queue (503)"),
        ("expired_total", "expired",
         "Requests expired before dispatch (504)"),
        ("error_total", "errors", "Requests failed by batch errors"),
        ("batches_total", "batches", "Batches dispatched"),
        ("batched_requests_total", "batched_requests",
         "Requests served inside batches"),
        ("batched_rows_total", "batched_rows",
         "Rows dispatched (pre-padding)"),
        ("bucket_rows_total", "bucket_rows",
         "Rows incl. bucket padding"),
    )

    def __init__(self, run_batch, max_batch=64, max_queue=256,
                 max_wait_ms=2.0, default_timeout_ms=1000.0,
                 name="batcher", model=None):
        self.name = name
        #: label value for this batcher's registry series (the model
        #: name when owned by a ModelRegistry entry)
        self.model = model or name
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_wait = float(max_wait_ms) / 1000.0
        self.default_timeout = float(default_timeout_ms) / 1000.0
        self._lock = threading.Lock()
        self._have_work = threading.Condition(self._lock)
        # weighted-fair queuing (ISSUE 18): one FIFO per tenant
        # (bounded — keys are resolver output), dequeued by least
        # virtual-finish-tag so a burst from one tenant interleaves
        # with, instead of preceding, everyone else's requests. With
        # a single tenant (or no tenant table) every request lands in
        # one deque and the order is exactly the pre-18 FIFO.
        self._queues = {}              # tenant -> deque of _Request
        self._vtime = 0.0              # queue-wide virtual time
        self._vfinish = {}             # tenant -> last finish tag
        self._queued_rows = 0
        self._running = True
        # -- instruments: registry-backed (ISSUE 3), metrics() is the
        # JSON view over them --
        self._c = {
            key: telemetry.LazyChild(
                lambda s=suffix, h=help: telemetry.counter(
                    "veles_serving_%s_total" % s, h,
                    ("model",)).labels(self.model))
            for key, suffix, help in self.COUNTERS}
        self._h_latency = telemetry.LazyChild(
            lambda: telemetry.histogram(
                "veles_serving_latency_seconds",
                "Request latency enqueue -> batch completion",
                ("model",)).labels(self.model))
        self._g_queue = telemetry.LazyChild(
            lambda: telemetry.gauge(
                "veles_serving_queue_rows",
                "Rows pending in the batcher queue",
                ("model",)).labels(self.model))
        self._completions = collections.deque(maxlen=4096)
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name="%s-worker" % name)
        self._thread.start()

    # -- client side ---------------------------------------------------

    def submit(self, rows, timeout_ms=None, trace=None, tenant=None):
        """Enqueue ``rows`` (n, *sample); -> a wait()able handle.
        Raises :class:`QueueFull` when the queue is at capacity.
        ``trace`` tags the request's queue-wait span with the
        caller's trace context; ``tenant`` (resolver output) keys the
        weighted-fair queue."""
        n = int(rows.shape[0])
        if n < 1 or n > self.max_batch:
            raise ValueError("request rows %d outside [1, %d]"
                             % (n, self.max_batch))
        timeout = timeout_seconds(timeout_ms, self.default_timeout)
        req = _Request(rows, time.monotonic() + timeout, trace=trace,
                       tenant=tenant)
        with self._lock:
            if not self._running:
                raise RuntimeError("batcher is closed")
            if self._queued_rows + n > self.max_queue:
                self._c["shed_total"].get().inc()
                raise QueueFull(
                    "queue full (%d rows pending, max %d)"
                    % (self._queued_rows, self.max_queue))
            self._c["requests_total"].get().inc()
            start = max(self._vtime, self._vfinish.get(tenant, 0.0))
            req.vft = start + n / tenants.weight(tenant)
            self._vfinish[tenant] = req.vft
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = collections.deque()
            q.append(req)
            self._queued_rows += n
            self._g_queue.get().set(self._queued_rows)
            self._have_work.notify()
        return req

    def predict(self, rows, timeout_ms=None, trace=None, tenant=None):
        """submit + wait; raises DeadlineExceeded / the batch error."""
        req = self.submit(rows, timeout_ms=timeout_ms, trace=trace,
                          tenant=tenant)
        req.event.wait(timeout=(req.deadline - time.monotonic())
                       + self.max_wait + 30.0)
        if req.error is not None:
            raise req.error
        if not req.event.is_set():
            raise DeadlineExceeded("no result before deadline")
        return req.result

    # -- worker --------------------------------------------------------

    def _head_locked(self):
        """The next request under weighted fairness: the least
        virtual-finish-tag among the per-tenant FIFO heads (tag ties
        broken by tenant name for determinism). Caller holds the
        lock; at least one queue is non-empty."""
        return min((q[0] for q in self._queues.values() if q),
                   key=lambda r: (r.vft, r.tenant or ""))

    def _collect(self):
        """Wait for work, then drain up to ``max_batch`` rows — holding
        the batch open at most ``max_wait`` past the OLDEST request's
        arrival (late joiners don't extend the window)."""
        with self._lock:
            while self._running and not self._queued_rows:
                self._have_work.wait()
            if not self._running and not self._queued_rows:
                return None
            oldest = min(q[0].t_enqueue
                         for q in self._queues.values() if q)
            close_at = oldest + self.max_wait
            while self._running:
                left = close_at - time.monotonic()
                if self._queued_rows >= self.max_batch or left <= 0:
                    break
                self._have_work.wait(timeout=left)
            batch, total = [], 0
            while self._queued_rows:
                head = self._head_locked()
                n = head.rows.shape[0]
                if batch and total + n > self.max_batch:
                    break
                if batch and head.rows.shape[1:] != \
                        batch[0].rows.shape[1:]:
                    # a differently-shaped request (possible when the
                    # archive records no input_sample_shape) starts its
                    # own batch: concatenating would fail the WHOLE
                    # dispatch and 500 innocent co-batched requests
                    break
                q = self._queues[head.tenant]
                req = q.popleft()
                if not q:
                    del self._queues[head.tenant]
                self._vtime = max(self._vtime, req.vft)
                self._queued_rows -= n
                batch.append(req)
                total += n
            self._g_queue.get().set(self._queued_rows)
            return batch

    def _worker(self):
        import numpy
        while True:
            batch = self._collect()
            if batch is None:
                return
            now = time.monotonic()
            live = []
            for req in batch:
                if req.deadline < now:
                    req.error = DeadlineExceeded(
                        "expired %.0fms before dispatch"
                        % ((now - req.deadline) * 1000))
                    self._c["expired_total"].get().inc()
                    req.event.set()
                else:
                    live.append(req)
            if not live:
                continue
            rows = numpy.concatenate([r.rows for r in live], axis=0) \
                if len(live) > 1 else live[0].rows
            t_dispatch = time.perf_counter()
            try:
                outputs, bucket = self._run_batch(rows)
            except Exception as exc:
                self.warning("batch of %d failed: %s: %s",
                             len(live), type(exc).__name__, exc)
                self._c["error_total"].get().inc(len(live))
                for req in live:
                    req.error = exc
                    req.event.set()
                continue
            done = time.monotonic()
            done_perf = time.perf_counter()
            off = 0
            for req in live:
                n = req.rows.shape[0]
                req.result = outputs[off:off + n]
                off += n
                req.event.set()
            if telemetry.tracer.active:
                self._trace_batch(live, t_dispatch, done_perf, bucket)
            # model-health drift gauges (ISSUE 15): mean output
            # entropy + top-1 margin — the monitor strides the
            # computation (every Nth batch per model), so this call
            # is a dict tick on the off-batches; ignored for
            # non-categorical shapes
            from veles import model_health
            model_health.get_model_monitor().observe_serving(
                self.model, outputs)
            self._c["batches_total"].get().inc()
            self._c["batched_requests_total"].get().inc(len(live))
            self._c["batched_rows_total"].get().inc(rows.shape[0])
            self._c["bucket_rows_total"].get().inc(bucket)
            latency = self._h_latency.get()
            with self._lock:
                for req in live:
                    latency.observe(done - req.t_enqueue)
                    self._completions.append(done)

    def _trace_batch(self, live, t_dispatch, done_perf, bucket):
        """Spans for one dispatched batch: a per-request queue-wait
        span in each request's own trace, plus ONE execute span for
        the shared forward (parented on the first traced request —
        batching is many-to-one by nature; the rest correlate via
        their queue spans' timeline overlap)."""
        parent = next((r.trace for r in live if r.trace is not None),
                      None)
        args = {"model": self.model, "requests": len(live),
                "bucket": bucket}
        if parent is not None:
            args.update(parent.child().span_args())
        telemetry.tracer.add_complete(
            "serving.execute", t_dispatch, done_perf - t_dispatch,
            **args)
        for req in live:
            qargs = {"model": self.model,
                     "rows": int(req.rows.shape[0])}
            if req.trace is not None:
                qargs.update(req.trace.child().span_args())
            telemetry.tracer.add_complete(
                "serving.queue", req.t_perf,
                t_dispatch - req.t_perf, **qargs)

    def close(self, zero_gauge=True):
        """``zero_gauge=False`` is for the hot-reload path: the
        replacement batcher shares this model's queue-gauge series and
        is already live, so the dying batcher must not stomp it."""
        with self._lock:
            self._running = False
            self._have_work.notify_all()
        self._thread.join(timeout=5)
        # fail anything still queued rather than leaving waiters hung
        # — UNDER the lock: if the join timed out (worker wedged in a
        # long run_batch) the worker still popleft()s concurrently,
        # and its own in-flight batch is no longer in the queue, so
        # completed requests are never clobbered here
        with self._lock:
            for q in self._queues.values():
                while q:
                    req = q.popleft()
                    req.error = RuntimeError("batcher closed")
                    req.event.set()
            self._queues.clear()
            self._queued_rows = 0
            if zero_gauge:
                self._g_queue.get().set(0)

    # -- metrics -------------------------------------------------------

    def metrics(self, rps_window=10.0):
        """The JSON view over the registry instruments — exact
        pre-registry key shape (regression-tested)."""
        c = {key: int(self._c[key].get().value)
             for key, _, _ in self.COUNTERS}
        latency = self._h_latency.get()
        with self._lock:
            queued = self._queued_rows
            now = time.monotonic()
            recent = [t for t in self._completions
                      if t > now - rps_window]
        m = {
            "queue_depth": queued,
            "requests_total": c["requests_total"],
            "shed_total": c["shed_total"],
            "expired_total": c["expired_total"],
            "error_total": c["error_total"],
            "batches_total": c["batches_total"],
            "batch_fill_ratio": round(
                c["batched_requests_total"]
                / max(c["batches_total"], 1), 3),
            "bucket_pad_ratio": round(
                c["bucket_rows_total"]
                / max(c["batched_rows_total"], 1), 3),
            # completions in the window over the WHOLE window: a
            # time-since-oldest denominator read ~1000 rps off a
            # single fresh completion
            "requests_per_sec": round(
                len(recent) / rps_window, 2),
        }
        p50 = latency.percentile(0.5)
        if p50 is not None:
            m["latency_ms_p50"] = round(p50 * 1000, 3)
            m["latency_ms_p99"] = round(
                latency.percentile(0.99) * 1000, 3)
        return m
