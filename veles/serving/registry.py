"""Model registry: named models, versions, hot reload.

Each registered model is a :class:`ServedModel` wiring one
:class:`ArchiveModel` (the weights + architecture, from an
``export_inference`` artifact directory) into an
:class:`InferenceEngine` (compiled forward cache) and a
:class:`MicroBatcher` (request coalescing). A model may additionally
be refreshed from a snapshotter checkpoint — local file or
``http(s)://`` URI through :class:`veles.snapshotter.HTTPSnapshotStore`
— which is how a serving process tracks a training run's best
checkpoint without re-exporting.

Hot reload (:meth:`ModelRegistry.reload`) re-reads the model's source
in place and atomically swaps it under the SAME name with a bumped
version; in-flight batches finish on the old params, the next batch
sees the new ones. When the architecture signature is unchanged the
engine keeps its compiled programs (params are runtime arguments) —
reload costs one host→device upload, no recompilation.
"""

import os
import threading
import time

from veles import telemetry
from veles.logger import Logger
from veles.serving.batcher import MicroBatcher
from veles.serving.engine import InferenceEngine
from veles.serving.model import ArchiveModel

_C_REFRESH_FAILURES = telemetry.LazyChild(lambda: telemetry.counter(
    "veles_serving_refresh_failures_total",
    "Hot reloads that failed and degraded to the loaded version",
    ("model",)))


class ServedModel:
    """One registry entry: model + engine + batcher + metadata."""

    def __init__(self, name, model, engine, batcher, source,
                 checkpoint=None, refresh_store=None):
        self.name = name
        self.model = model
        self.engine = engine
        self.batcher = batcher
        self.source = source
        self.checkpoint = checkpoint
        #: snapshot-store target (dir or http base) the refresh poll
        #: scans for newer healthy checkpoints (ISSUE 16 rolling
        #: refresh); derived from ``checkpoint`` when unset
        self.refresh_store = refresh_store
        self.version = 1
        self.loaded_at = time.time()
        #: lazy decode plane (ISSUE 11): built by
        #: ModelRegistry.decoder() on the first /v1/generate for a
        #: generative archive — a classifier-only registry never pays
        #: for a KV pool
        self.decoder = None
        self._decoder_lock = threading.Lock()
        self._closed = False
        #: readiness signal (veles/health.py): False only while a
        #: REQUESTED warmup is still compiling the bucket ladder — a
        #: model loaded without warmup compiles on first request and
        #: must not wedge readiness (the probe would reject the very
        #: request that warms it)
        self.warm = True

    def predict(self, rows, timeout_ms=None, trace=None, tenant=None):
        return self.batcher.predict(rows, timeout_ms=timeout_ms,
                                    trace=trace, tenant=tenant)

    def cache_bytes(self):
        """Forward-cache memory ESTIMATE for this entry (ISSUE 10
        memory accounting): the params pytree (host copy, plus the
        device upload on the jit backend) and a per-compiled-bucket
        input+output buffer guess. A size proxy the health ring can
        trend, not an allocator meter."""
        from veles.serving.quant import tree_nbytes
        params = tree_nbytes(self.model.params)
        total = params * (2 if self.engine.backend == "jit" else 1)
        sample = self.model.input_sample_shape
        if sample:
            row = 4
            for d in sample:
                row *= int(d)
            # x2: the batch buffer in and a same-order output out
            total += sum(b * row * 2
                         for b in self.engine.compiled_buckets)
        decoder = self.decoder
        if decoder is not None:
            # the paged KV pool is preallocated forward-cache memory
            # too (ISSUE 11): slots exist whether or not occupied
            total += decoder.engine.pool.nbytes()
        return total

    def describe(self):
        from veles.serving.decode import DecodePlan
        doc = {
            "name": self.name,
            "version": self.version,
            "workflow": self.model.workflow_name,
            "source": self.source,
            "checkpoint": self.checkpoint,
            "input_sample_shape": self.model.input_sample_shape,
            "units": [s["type"] for s in self.model.units],
            "backend": self.engine.backend,
            "quantize": self.engine.quantize,
            "compiled_buckets": self.engine.compiled_buckets,
            "loaded_at": self.loaded_at,
            "generative": DecodePlan.probe(self.model),
        }
        decoder = self.decoder
        if decoder is not None:
            doc["decode"] = {
                "kv_pool_slots": decoder.engine.pool.n_slots,
                "max_len": decoder.engine.max_len,
            }
        return doc

    def close(self, zero_gauge=True):
        """``zero_gauge=False`` is the hot-reload path (see
        MicroBatcher.close). The decoder handoff happens under
        _decoder_lock so an unload racing a first /v1/generate can
        never leak a just-built decode plane: either close() takes
        it here, or the builder sees _closed and refuses."""
        with self._decoder_lock:
            self._closed = True
            decoder = self.decoder
            self.decoder = None
        if decoder is not None:
            decoder.close()
        self.batcher.close(zero_gauge=zero_gauge)


class ModelRegistry(Logger):
    """Thread-safe name -> :class:`ServedModel` map."""

    def __init__(self, backend="auto", max_batch=64, max_queue=256,
                 max_wait_ms=2.0, default_timeout_ms=1000.0,
                 decode_slots=8, decode_max_len=256,
                 decode_max_queue=64, quantize_weights="none"):
        self.name = "registry"
        self.backend = backend
        #: at-rest weight quantization (serving/quant.py, ISSUE 14):
        #: every loaded model's params ride int8/fp8 host AND device,
        #: densified at dispatch — validated here so a typo'd
        #: --quantize-weights fails at configuration time
        from veles.serving.quant import validate_mode
        validate_mode(quantize_weights, "quantize_weights")
        self.quantize_weights = quantize_weights
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.max_wait_ms = float(max_wait_ms)
        self.default_timeout_ms = float(default_timeout_ms)
        #: decode-plane geometry (ISSUE 11): KV pool width (the shared
        #: decode batch) and per-slot sequence length
        self.decode_slots = int(decode_slots)
        self.decode_max_len = int(decode_max_len)
        self.decode_max_queue = int(decode_max_queue)
        self._lock = threading.Lock()
        self._models = {}
        #: per-model count of failed hot reloads (checkpoint store
        #: down, bad archive): the registry DEGRADES — keeps serving
        #: the loaded version — instead of dying, and these counters
        #: plus the store's circuit-breaker state surface the
        #: degradation through /metrics
        self._refresh_failures = {}

    # -- lifecycle -----------------------------------------------------

    def load(self, name, source, checkpoint=None, warmup=False,
             refresh_store=None):
        """Load (or replace) model ``name`` from artifact directory
        ``source``; optionally refresh its params from ``checkpoint``
        and precompile the bucket ladder. ``refresh_store`` records
        the snapshot-store target :meth:`refresh_newest` polls."""
        model = ArchiveModel.from_dir(source)
        if checkpoint:
            model.load_checkpoint(checkpoint)
        with self._lock:
            old = self._models.get(name)
            if old is not None and \
                    old.model.signature() == model.signature():
                # same architecture: swap params, keep the compiled
                # cache and the running batcher
                old.model = model
                old.engine.set_model(model, params_only=True)
                if old.decoder is not None:
                    # decode programs keep too (params are runtime
                    # args); in-flight sequences finish on whichever
                    # tree their next step reads — same contract as
                    # in-flight predict batches
                    old.decoder.engine.set_params(model)
                old.source = source
                old.checkpoint = checkpoint
                if refresh_store:
                    old.refresh_store = refresh_store
                old.version += 1
                old.loaded_at = time.time()
                self._version_gauge(name).set(old.version)
                self.info("model %s reloaded in place -> v%d",
                          name, old.version)
                return old
            engine = InferenceEngine(model, backend=self.backend,
                                     max_batch=self.max_batch,
                                     quantize=self.quantize_weights)
            batcher = MicroBatcher(
                engine.predict, max_batch=self.max_batch,
                max_queue=self.max_queue,
                max_wait_ms=self.max_wait_ms,
                default_timeout_ms=self.default_timeout_ms,
                name="batcher-%s" % name, model=name)
            entry = ServedModel(name, model, engine, batcher, source,
                                checkpoint, refresh_store=refresh_store)
            if old is not None:
                entry.version = old.version + 1
                if refresh_store is None:
                    entry.refresh_store = old.refresh_store
            self._models[name] = entry
        self._version_gauge(name).set(entry.version)
        self._checkpoint_gauges(name)
        # scrape-time evaluation: buckets compile lazily and reloads
        # swap entries, so a stored value would go stale immediately.
        # Unloaded names read 0 (the series stays, the memory is gone).
        telemetry.gauge(
            "veles_serving_forward_cache_bytes",
            "Estimated bytes held by the model's forward cache "
            "(params + compiled bucket buffers; veles/profiling.py "
            "memory accounting)", ("model",)).labels(
                name).set_function(
                    lambda n=name: self._entry_cache_bytes(n))
        if old is not None:
            # close OUTSIDE the lock: draining the old batcher (and
            # the old decode plane's worker + KV pool, when one was
            # built) can block for seconds and must not stall get()
            # for every other model's request threads. The
            # replacement batcher owns the model's queue-gauge
            # series now — don't zero it.
            old.close(zero_gauge=False)
        if warmup:
            entry.warm = False
            try:
                entry.engine.warmup()
            finally:
                entry.warm = True
        self.info("model %s v%d loaded from %s (%d units, backend "
                  "%s)", name, entry.version, source,
                  len(model.units), entry.engine.backend)
        return entry

    def reload(self, name):
        """Hot reload from the entry's recorded source+checkpoint.

        A refresh failure (flapping snapshot endpoint — possibly
        fast-failed by its circuit breaker — or a half-written
        archive) must not take down a serving process that has a
        perfectly good model in memory: the failure is counted and
        the CURRENT entry keeps serving unchanged."""
        entry = self.get(name)
        try:
            return self.load(name, entry.source,
                             checkpoint=entry.checkpoint)
        except Exception as exc:
            with self._lock:
                self._refresh_failures[name] = \
                    self._refresh_failures.get(name, 0) + 1
                n = self._refresh_failures[name]
            _C_REFRESH_FAILURES.get().labels(name).inc()
            telemetry.record_event("reload_failed", model=name,
                                   error=str(exc))
            self.warning(
                "hot reload of %s failed (%s: %s; failure #%d) — "
                "still serving v%d", name, type(exc).__name__, exc,
                n, entry.version)
            return entry

    # -- rolling refresh (ISSUE 16) ------------------------------------

    def refresh_newest(self, name, store_target=None):
        """The refresh poll: scan the model's snapshot store for the
        newest HEALTHY checkpoint and hot-load it when it is newer
        than what is served.

        Every diverged blob encountered on the way down is skipped
        WITH ITS NAME in the log, an event in the flight recorder and
        a count in ``veles_checkpoint_diverged_skips_total`` — a
        wedged rollout must be diagnosable from one scrape. Corrupt
        and legacy blobs fall through silently (the scan already
        ranks them last). Store/transport failures degrade like
        :meth:`reload`: counted, logged, still serving.

        -> the loaded checkpoint path, or None (nothing newer, or
        the refresh degraded)."""
        from veles import snapshotter
        entry = self.get(name)
        target = store_target or entry.refresh_store
        if target is None and entry.checkpoint:
            # a concrete checkpoint path implies its store
            ckpt = str(entry.checkpoint)
            target = (ckpt.rsplit("/", 1)[0]
                      if ckpt.startswith(("http://", "https://"))
                      else os.path.dirname(ckpt))
        if not target:
            raise ValueError(
                "model %r has no snapshot store to refresh from "
                "(pass store_target or load with refresh_store=)"
                % name)
        served_wall = entry.model.checkpoint_meta.get("wall_time")
        try:
            infos = snapshotter.scan_checkpoints(target)
        except Exception as exc:
            with self._lock:
                self._refresh_failures[name] = \
                    self._refresh_failures.get(name, 0) + 1
            _C_REFRESH_FAILURES.get().labels(name).inc()
            self.warning("refresh poll of %s: store scan of %s failed "
                         "(%s: %s) — still serving v%d", name, target,
                         type(exc).__name__, exc, entry.version)
            return None
        for info in infos:
            if info.status != "valid":
                continue
            if info.wall_time is not None and served_wall \
                    and info.wall_time <= float(served_wall):
                break               # nothing newer than what we serve
            if info.health_verdict == "diverged":
                snapshotter._count_diverged_skip()
                telemetry.record_event("refresh_skipped_diverged",
                                       model=name,
                                       checkpoint=info.name)
                self.warning(
                    "refresh poll of %s SKIPPED diverged checkpoint "
                    "%s — still serving v%d (staleness reflects the "
                    "skip)", name, info.name, entry.version)
                continue
            path = ("%s/%s" % (str(target).rstrip("/"), info.name)
                    if str(target).startswith(("http://", "https://"))
                    else os.path.join(str(target), info.name))
            try:
                self.load(name, entry.source, checkpoint=path,
                          refresh_store=target)
            except Exception as exc:
                with self._lock:
                    self._refresh_failures[name] = \
                        self._refresh_failures.get(name, 0) + 1
                _C_REFRESH_FAILURES.get().labels(name).inc()
                telemetry.record_event("reload_failed", model=name,
                                       error=str(exc))
                self.warning(
                    "refresh of %s from %s failed (%s: %s) — still "
                    "serving v%d", name, path, type(exc).__name__,
                    exc, entry.version)
                return None
            telemetry.record_event("refresh_loaded", model=name,
                                   checkpoint=info.name,
                                   wall_time=info.wall_time)
            return path
        return None

    def _checkpoint_gauges(self, name):
        """Scrape-time gauges over the served checkpoint's MANIFEST:
        the absolute walls the rolling-refresh orchestrator compares
        across replicas, and the model's own staleness point."""
        from veles.continual import install_point_gauge
        telemetry.gauge(
            "veles_serving_checkpoint_wall_seconds",
            "MANIFEST wall time of the served checkpoint (0 = "
            "serving the export archive, no checkpoint loaded)",
            ("model",)).labels(name).set_function(
                lambda n=name: self._ckpt_meta(n, "wall_time"))
        telemetry.gauge(
            "veles_serving_checkpoint_ingest_wall_seconds",
            "MANIFEST ingest_wall of the served checkpoint (0 = no "
            "continual stamp)", ("model",)).labels(name).set_function(
                lambda n=name: self._ckpt_meta(n, "ingest_wall"))
        install_point_gauge(
            "serving:%s" % name,
            lambda n=name: self._ckpt_meta(n, "ingest_wall") or None)

    def _ckpt_meta(self, name, key):
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            return 0.0
        value = entry.model.checkpoint_meta.get(key)
        try:
            return float(value)
        except (TypeError, ValueError):
            return 0.0

    def unload(self, name):
        with self._lock:
            entry = self._models.pop(name)
            # a future model loaded under the same name must not
            # inherit this one's degradation history
            self._refresh_failures.pop(name, None)
        entry.close()

    def close(self):
        with self._lock:
            entries = list(self._models.values())
            self._models.clear()
        for entry in entries:
            entry.close()

    def _entry_cache_bytes(self, name):
        with self._lock:
            entry = self._models.get(name)
        return entry.cache_bytes() if entry is not None else 0

    @staticmethod
    def _version_gauge(name):
        return telemetry.gauge(
            "veles_serving_model_version",
            "Currently served model version", ("model",)).labels(name)

    # -- refresh-target admission --------------------------------------

    @staticmethod
    def _within_store(root, target):
        """True when ``target`` stays inside ``root`` (URL-prefix for
        http stores, normpath-prefix for directories — ``..`` hops
        are normalized away before the check)."""
        if root.startswith(("http://", "https://")):
            root = root.rstrip("/")
            return target == root or target.startswith(root + "/")
        root_abs = os.path.normpath(os.path.abspath(root))
        t_abs = os.path.normpath(os.path.abspath(target))
        return t_abs == root_abs or t_abs.startswith(root_abs + os.sep)

    def resolve_refresh_target(self, entry, checkpoint=None,
                               store=None):
        """Admission bound for client-supplied refresh targets (zlint
        ``untrusted-path``): ``POST /refresh`` bodies cross the HTTP
        trust boundary, so a path they name must stay within a store
        this entry was CONFIGURED with server-side — its
        ``refresh_store``, the directory of its loaded checkpoint, or
        its artifact source. -> ``(checkpoint, store)`` admitted
        values (None where absent); raises ValueError (-> 400) for
        anything outside those roots."""
        roots = []
        if entry.refresh_store:
            roots.append(str(entry.refresh_store))
        if entry.checkpoint:
            ckpt = str(entry.checkpoint)
            roots.append(ckpt.rsplit("/", 1)[0]
                         if ckpt.startswith(("http://", "https://"))
                         else (os.path.dirname(ckpt) or "."))
        if entry.source:
            roots.append(str(entry.source))
        admitted = []
        for target in (checkpoint, store):
            if target is None or target == "":
                admitted.append(None)
                continue
            if not isinstance(target, str):
                raise ValueError("refresh target must be a string "
                                 "path, got %s"
                                 % type(target).__name__)
            if not any(self._within_store(root, target)
                       for root in roots):
                raise ValueError(
                    "refresh target %r is outside the model's "
                    "configured stores — load the entry with "
                    "refresh_store= to allow a new location" % target)
            admitted.append(target)
        return tuple(admitted)

    # -- lookup --------------------------------------------------------

    def get(self, name):
        with self._lock:
            try:
                return self._models[name]
            except KeyError:
                raise KeyError("no model %r (serving: %s)"
                               % (name, sorted(self._models) or "none"))

    def names(self):
        with self._lock:
            return sorted(self._models)

    def decoder(self, name):
        """The model's continuous-batching decode plane, built on
        first use (:class:`~veles.serving.decode.ContinuousBatcher`).
        Raises :class:`KeyError` for unknown names and
        :class:`ValueError` when the archive cannot generate (not an
        LM: no leading embedding / non-causal attention)."""
        entry = self.get(name)
        decoder = entry.decoder
        if decoder is not None:
            return decoder
        from veles.serving.decode import (ContinuousBatcher,
                                          GenerativeEngine)
        with entry._decoder_lock:
            if entry._closed:
                # raced an unload/replace: the entry will never be
                # served again, so a decoder built now would leak
                raise KeyError("model %r was unloaded" % name)
            if entry.decoder is None:
                engine = GenerativeEngine(
                    entry.model, n_slots=self.decode_slots,
                    max_len=self.decode_max_len,
                    name="decode-engine-%s" % name)
                entry.decoder = ContinuousBatcher(
                    engine, max_queue=self.decode_max_queue,
                    name="decode-%s" % name, model=name)
                self.info(
                    "decode plane for %s: %d KV slots x %d tokens "
                    "(%.1f MB pool)", name, engine.pool.n_slots,
                    engine.max_len, engine.pool.nbytes() / 1048576.0)
            return entry.decoder

    def describe(self):
        with self._lock:
            entries = list(self._models.values())
        return [e.describe() for e in entries]

    def metrics(self):
        with self._lock:
            entries = list(self._models.items())
            failures = dict(self._refresh_failures)
        out = {}
        for name, e in entries:
            m = dict(e.batcher.metrics(), version=e.version,
                     compiled_buckets=e.engine.compiled_buckets,
                     refresh_failures=failures.get(name, 0))
            store = self._checkpoint_store(e.checkpoint)
            if store is not None:
                m["checkpoint_store"] = store.metrics()
            decoder = e.decoder
            if decoder is not None:
                # the decode plane's view: tokens/s, KV occupancy,
                # queue — what velescli top renders per target
                m["decode"] = decoder.metrics()
            out[name] = m
        return out

    @staticmethod
    def _checkpoint_store(checkpoint):
        if not checkpoint or not str(checkpoint).startswith(
                ("http://", "https://")):
            return None
        from veles.snapshotter import store_for
        return store_for(str(checkpoint))[0]
