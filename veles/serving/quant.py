"""At-rest weight quantization for the serving planes (ISSUE 14).

A serving process is capacity-bound by ``veles_serving_forward_cache
_bytes``: every loaded model holds its f32 params twice (host at-rest
copy + device upload on the jit backend), and the decode plane's KV
pool on top. LLM.int8()-style per-tensor weight quantization (Dettmers
et al., 2022) halves the weight half of that bill at negligible logit
error — weights tolerate 8-bit per-tensor quantization far better
than activations, and serving never updates them.

The representation is :class:`QuantizedTensor`: the quantized payload
(``int8`` = uint8 + affine min/scale — the SAME math the gradient wire
codec uses, ``veles/compression.py``; ``fp8`` = float8_e4m3fn + a
symmetric per-tensor scale) registered as a **jax pytree node**, so
``device_put``/``jit`` thread it through untouched and the scale rides
as a runtime leaf — a hot reload re-uploads fresh scales without
invalidating any compiled program (the same contract plain params
have). Dequantization happens at DISPATCH: ``ArchiveModel.apply`` and
the decode programs densify each unit's tree inside the trace, where
XLA fuses the convert+scale into the consumer matmul — the at-rest and
device copies stay 1 byte/element.

Policy: only matrix-shaped tensors (``ndim >= 2``) of at least
``MIN_QUANT_SIZE`` elements quantize — biases and layernorm vectors
are capacity-irrelevant and numerically twitchy, so they stay f32.
Stacked-layer tensors (layers, d, h) quantize per-TENSOR across the
stack; the parity bounds in ``tests/test_wquant.py`` gate both modes.
"""

import threading

import numpy

from veles.compression import _int8_code

#: accepted --quantize-weights values
MODES = ("none", "int8", "fp8")

#: smallest element count worth quantizing (below this the scale
#: bookkeeping rivals the savings and vectors lose real precision)
MIN_QUANT_SIZE = 1024

#: float8_e4m3fn max finite — the symmetric fp8 scale target
_FP8_MAX = 448.0

_registered = False
_register_lock = threading.Lock()


def _ensure_registered():
    """Register the pytree node lazily — quant must import (and the
    numpy backend must run) on hosts without jax. Locked: two engines
    quantizing their first model concurrently must not race the
    check-then-register (jax raises on a duplicate registration)."""
    global _registered
    if _registered:
        return
    try:
        import jax
    except Exception:
        return
    with _register_lock:
        if _registered:
            return
        jax.tree_util.register_pytree_node(
            QuantizedTensor,
            lambda t: ((t.q, t.scale, t.zero), (t.mode,)),
            lambda aux, kids: QuantizedTensor(aux[0], *kids))
        _registered = True


class QuantizedTensor:
    """One at-rest quantized weight: payload + per-tensor scale (and
    zero point for the affine int8 form). Exposes ``shape``/``nbytes``
    so the registry's ``signature()``/``cache_bytes()`` accounting
    reads it like any array; :meth:`dense` reconstructs f32 at
    dispatch (traced on the jit path)."""

    __slots__ = ("mode", "q", "scale", "zero")

    def __init__(self, mode, q, scale, zero):
        self.mode = mode
        self.q = q
        self.scale = scale
        self.zero = zero

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes + self.zero.nbytes

    def dense(self, xp, payload=None):
        """f32 reconstruction with ``xp`` math (numpy on the host
        path, jax.numpy inside a trace — where the convert+scale
        fuses into the consumer). ``payload`` (default the whole
        ``q``) lets a caller dequantize just a gathered/sliced piece
        — the per-tensor scale applies to any sub-block."""
        q = self.q if payload is None else payload
        f32 = numpy.float32 if xp is numpy else "float32"
        if self.mode == "int8":
            return q.astype(f32) * self.scale + self.zero
        return q.astype(f32) * self.scale

    def __repr__(self):
        return ("QuantizedTensor(%s, shape=%s, %d bytes)"
                % (self.mode, self.q.shape, self.nbytes))


def quantize_tensor(arr, mode):
    """One f32 ndarray -> :class:`QuantizedTensor` (``int8``/``fp8``).
    An already-quantized leaf in the SAME mode passes through (the
    re-quantize path after a checkpoint refresh mixes fresh f32 and
    untouched quantized leaves); a different mode densifies first."""
    _ensure_registered()
    if isinstance(arr, QuantizedTensor):
        if arr.mode == mode:
            return arr
        arr = arr.dense(numpy)
    a = numpy.ascontiguousarray(arr, numpy.float32)
    if mode == "int8":
        payload, _ = _int8_code(a, with_decoded=False)
        return QuantizedTensor(
            "int8", payload["data"],
            numpy.float32(payload["scale"]),
            numpy.float32(payload["zero"]))
    if mode == "fp8":
        import ml_dtypes
        amax = float(numpy.abs(a).max()) if a.size else 0.0
        scale = (amax / _FP8_MAX) if amax > 0 else 1.0
        q = (a / numpy.float32(scale)).astype(ml_dtypes.float8_e4m3fn)
        return QuantizedTensor("fp8", q, numpy.float32(scale),
                               numpy.float32(0.0))
    raise ValueError("unknown weight-quantization mode %r (known: %s)"
                     % (mode, ", ".join(MODES)))


def _eligible(arr):
    if isinstance(arr, QuantizedTensor):
        return True
    return (getattr(arr, "ndim", 0) >= 2
            and getattr(arr, "size", 0) >= MIN_QUANT_SIZE
            and numpy.issubdtype(
                numpy.asarray(arr).dtype, numpy.floating))


def validate_mode(mode, param="quantize"):
    """THE mode guard — raise on anything outside :data:`MODES`.
    Engine, registry and tree all call this one copy, so the error
    text (and a future mode) cannot drift between layers."""
    if mode not in MODES:
        raise ValueError("%s must be one of %s, got %r"
                         % (param, "|".join(MODES), mode))


def quantize_tree(params, mode):
    """``{unit: {key: array}}`` -> the same tree with every eligible
    leaf quantized IN a fresh tree (callers overwrite the at-rest
    reference). ``mode='none'`` returns the input untouched."""
    validate_mode(mode)
    if mode == "none":
        return params
    return {
        name: {key: (quantize_tensor(a, mode) if _eligible(a) else a)
               for key, a in tree.items()}
        for name, tree in params.items()}


def dense_params(xp, tree):
    """One unit's param dict with every quantized leaf reconstructed —
    the dispatch-time hook. Identity-cheap when nothing is quantized
    (the common non-quantized deployment pays one isinstance per
    leaf)."""
    if not any(isinstance(v, QuantizedTensor) for v in tree.values()):
        return tree
    return {k: (v.dense(xp) if isinstance(v, QuantizedTensor) else v)
            for k, v in tree.items()}


def gather_rows(xp, leaf, idx):
    """``leaf[idx]`` densified: for a quantized leaf the 1-byte
    payload is indexed FIRST and only the gathered slice dequantizes.
    The embedding consumer is a gather, not a matmul — densifying the
    whole vocab table inside every decode step would re-materialize
    f32 rows per token and erase the bandwidth saving the at-rest
    format buys. ``idx`` is anything ndarray indexing takes (token
    ids, a position array, a slice)."""
    if isinstance(leaf, QuantizedTensor):
        return leaf.dense(xp, leaf.q[idx])
    return leaf[idx]


def tree_nbytes(params):
    """Summed leaf bytes of a (possibly quantized) params tree — what
    ``cache_bytes()`` charges for one at-rest copy."""
    return sum(a.nbytes for tree in params.values()
               for a in tree.values())
