"""``velescli loadgen`` — open-loop load generation with tenant
mixes (ISSUE 18).

The QoS layer's proof harness: a Poisson-arrival (open-loop)
generator drives mixed predict/generate traffic at a routed fleet or
a single replica, per arrival picking a tenant from the configured
mix and stamping its ``x-veles-tenant`` header, and reports
goodput/p99/shed-rate CURVES per tenant across an arrival-rate ramp.

Open loop matters: a closed-loop client (send, wait, send) slows
down exactly when the service does, flattering p99 at the point of
saturation — the "coordinated omission" trap. Here arrivals are
scheduled by the clock (exponential inter-arrival gaps, never waiting
on completions), so offered load keeps arriving while the fleet
chokes and the shed/latency curves show the choke honestly.

The summary row is the capacity number ROADMAP item 4 asks for::

    {"metric": "routed_capacity_rps_at_p99_slo", "value": R, ...}

— the highest offered rps stage at which the FIRST configured tenant
(the "compliant" one by convention) kept its p99 inside
``--p99-slo-ms`` with a shed rate under ``--max-shed``. ``bench.py
--self-check`` knows this key is higher-is-better.
"""

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

#: dispatch pool width: enough in-flight sockets that the generator
#: never blocks on completions at test-scale rates (true open loop up
#: to ~hundreds of concurrently outstanding requests)
MAX_WORKERS = 64


class _TenantMix:
    """Weighted tenant shares; ``pick(rng)`` draws one arrival."""

    def __init__(self, shares):
        # [(name, share)] normalized; order preserved (first tenant
        # is the capacity row's compliant subject)
        total = sum(s for _, s in shares)
        self.names = [name for name, _ in shares]
        self._cum = []
        acc = 0.0
        for name, share in shares:
            acc += share / total
            self._cum.append((acc, name))

    def pick(self, rng):
        x = rng.random()
        for edge, name in self._cum:
            if x <= edge:
                return name
        return self._cum[-1][1]


def _parse_tenants(specs):
    """--tenant NAME[:SHARE] (repeatable) -> [(name, share)]."""
    out = []
    for spec in specs or ["anon"]:
        name, sep, share = spec.partition(":")
        if not name:
            raise SystemExit("--tenant %r: expected NAME[:SHARE]"
                             % spec)
        try:
            out.append((name, float(share) if sep else 1.0))
        except ValueError:
            raise SystemExit("--tenant %r: bad share" % spec)
    return out


def _fetch_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(q * len(sorted_vals) + 0.5) - 1))
    return sorted_vals[idx]


class _Stats:
    """One (stage, tenant) bucket; thread-safe counters + latency."""

    def __init__(self):
        self.lock = threading.Lock()
        self.offered = 0
        self.ok = 0
        self.shed = 0                # 429 quota + 503 shed/not-ready
        self.errors = 0
        self.latencies = []          # seconds, answered requests only

    def record(self, code, dt):
        with self.lock:
            if code is not None and 200 <= code < 300:
                self.ok += 1
                self.latencies.append(dt)
            elif code in (429, 503):
                self.shed += 1
            else:
                self.errors += 1

    def summary(self, duration):
        lat = sorted(self.latencies)
        p50 = _percentile(lat, 0.50)
        p99 = _percentile(lat, 0.99)
        return {
            "offered": self.offered, "ok": self.ok,
            "shed": self.shed, "errors": self.errors,
            "goodput_rps": round(self.ok / duration, 2),
            "shed_rate": round(self.shed / max(self.offered, 1), 4),
            "p50_ms": None if p50 is None else round(p50 * 1e3, 2),
            "p99_ms": None if p99 is None else round(p99 * 1e3, 2),
        }


def _one_request(url, body, tenant, timeout, stats):
    t0 = time.perf_counter()
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/json",
                 "x-veles-tenant": tenant})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
            code = resp.status
    except urllib.error.HTTPError as exc:
        exc.read()
        code = exc.code
    except Exception:
        code = None
    stats.record(code, time.perf_counter() - t0)


#: ceiling on the zero-sample the target's advertised geometry may
#: make us build — the /v1/models listing is the TARGET's data, and a
#: malicious or buggy target advertising [1 << 30] must not OOM the
#: load generator (zlint untrusted-geometry)
_MAX_SAMPLE_ELEMENTS = 1 << 20
_MAX_SAMPLE_RANK = 8


def _validated_shape(shape):
    """Bound target-advertised ``input_sample_shape`` before any
    allocation keys off it; -> a list of positive ints, or
    SystemExit naming the refused geometry."""
    dims = []
    total = 1
    for dim in list(shape)[:_MAX_SAMPLE_RANK]:
        try:
            dim = int(dim)
        except (TypeError, ValueError):
            raise SystemExit(
                "target advertises a non-numeric input_sample_shape "
                "entry %r" % (dim,))
        dims.append(max(dim, 1))
        total *= max(dim, 1)
    if len(list(shape)) > _MAX_SAMPLE_RANK \
            or total > _MAX_SAMPLE_ELEMENTS:
        raise SystemExit(
            "target advertises input_sample_shape %r (%d elements) — "
            "refusing to build a sample beyond %d elements"
            % (list(shape), total, _MAX_SAMPLE_ELEMENTS))
    return dims or [1]


def _predict_body(base, model_arg, timeout=10.0):
    """(model name, canned /v1/predict body, generative?) derived
    from the target's ``/v1/models`` listing — a zero-valued sample
    of the model's recorded input shape prices the real forward."""
    doc = _fetch_json(base + "/v1/models", timeout=timeout)
    models = doc.get("models") or []
    if not models:
        raise SystemExit("target serves no models")
    if model_arg:
        matches = [m for m in models if m.get("name") == model_arg]
        if not matches:
            raise SystemExit("target does not serve model %r "
                             "(has: %s)" % (model_arg, ", ".join(
                                 sorted(m.get("name", "?")
                                        for m in models))))
        m = matches[0]
    else:
        m = models[0]
    name = m["name"]
    shape = _validated_shape(m.get("input_sample_shape") or [1])

    def zeros(dims):
        if not dims:
            return 0.0
        return [zeros(dims[1:]) for _ in range(int(dims[0]))]

    body = json.dumps({"model": name,
                       "inputs": [zeros(shape)]}).encode()
    return name, body, bool(m.get("generative"))


def run_stage(base, rate, duration, mix, bodies, rng, pool,
              timeout_s, generate_ratio):
    """One open-loop stage at ``rate`` rps for ``duration`` seconds;
    -> {tenant: _Stats}. Arrivals are clock-scheduled; dispatch rides
    the pool so a slow reply NEVER delays the next arrival."""
    stats = {name: _Stats() for name in mix.names}
    predict_url, predict_body, generate_body = bodies
    futures = []
    t_next = time.monotonic()
    t_end = t_next + duration
    while t_next < t_end:
        now = time.monotonic()
        if t_next > now:
            time.sleep(t_next - now)
        tenant = mix.pick(rng)
        s = stats[tenant]
        s.offered += 1
        if generate_body is not None \
                and rng.random() < generate_ratio:
            url, body = base + "/v1/generate", generate_body
        else:
            url, body = predict_url, predict_body
        futures.append(pool.submit(
            _one_request, url, body, tenant, timeout_s, s))
        t_next += rng.expovariate(rate)
    # drain between stages: each stage's curve must price ITS offered
    # load, not inherit the previous stage's stragglers
    for f in futures:
        f.result()
    return stats


def build_loadgen_argparser():
    p = argparse.ArgumentParser(
        prog="velescli loadgen",
        description="Open-loop (Poisson-arrival) load generator "
                    "with tenant mixes and arrival-rate ramps; "
                    "reports per-tenant goodput/p99/shed curves and "
                    "the routed_capacity_rps_at_p99_slo bench row")
    p.add_argument("target", metavar="URL",
                   help="router or serving base URL "
                        "(http://host:port)")
    p.add_argument("--tenant", action="append", default=[],
                   metavar="NAME[:SHARE]",
                   help="tenant mix entry (repeatable; shares "
                        "normalize; default one 'anon' tenant). The "
                        "FIRST tenant is the compliant subject of "
                        "the capacity row")
    p.add_argument("--rps", action="append", type=float, default=[],
                   metavar="RATE",
                   help="offered arrival rate per ramp stage "
                        "(repeatable, e.g. --rps 20 --rps 50 "
                        "--rps 100; default 20)")
    p.add_argument("--duration", type=float, default=5.0,
                   metavar="SECS", help="seconds per ramp stage")
    p.add_argument("--model", default=None,
                   help="served model to drive (default: the "
                        "target's first)")
    p.add_argument("--generate-ratio", type=float, default=0.0,
                   metavar="FRAC",
                   help="fraction of arrivals sent to /v1/generate "
                        "(needs a generative model; non-streaming)")
    p.add_argument("--max-tokens", type=int, default=8,
                   help="decode budget per generate arrival")
    p.add_argument("--p99-slo-ms", type=float, default=250.0,
                   help="the compliant tenant's p99 objective the "
                        "capacity row is judged against")
    p.add_argument("--max-shed", type=float, default=0.01,
                   metavar="FRAC",
                   help="max compliant-tenant shed rate for a stage "
                        "to count as within capacity")
    p.add_argument("--timeout-ms", type=float, default=10000.0,
                   help="per-request client timeout")
    p.add_argument("--seed", type=int, default=1234,
                   help="arrival/tenant-pick RNG seed")
    p.add_argument("--json", action="store_true",
                   help="print ONE machine-readable report (the "
                        "bench row with per-stage curves in 'extra') "
                        "instead of the table")
    return p


def loadgen_main(argv=None):
    args = build_loadgen_argparser().parse_args(argv)
    base = args.target.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    rates = args.rps or [20.0]
    mix = _TenantMix(_parse_tenants(args.tenant))
    compliant = mix.names[0]
    rng = random.Random(args.seed)
    model, predict_body, generative = _predict_body(base, args.model)
    generate_body = None
    if args.generate_ratio > 0:
        if not generative:
            raise SystemExit("--generate-ratio: model %r is not "
                             "generative" % model)
        generate_body = json.dumps({
            "model": model, "prompt": [1, 2, 3],
            "max_tokens": args.max_tokens,
            "stream": False}).encode()
    bodies = (base + "/v1/predict", predict_body, generate_body)
    stages = []
    capacity = 0.0
    with ThreadPoolExecutor(max_workers=MAX_WORKERS,
                            thread_name_prefix="loadgen") as pool:
        for rate in rates:
            stats = run_stage(
                base, rate, args.duration, mix, bodies, rng, pool,
                args.timeout_ms / 1000.0, args.generate_ratio)
            per_tenant = {name: s.summary(args.duration)
                          for name, s in stats.items()}
            stages.append({"offered_rps": rate,
                           "duration_s": args.duration,
                           "tenants": per_tenant})
            c = per_tenant[compliant]
            if c["p99_ms"] is not None \
                    and c["p99_ms"] <= args.p99_slo_ms \
                    and c["shed_rate"] <= args.max_shed:
                capacity = max(capacity, rate)
    report = {
        "metric": "routed_capacity_rps_at_p99_slo",
        "value": capacity,
        "extra": {
            "target": base, "model": model,
            "compliant_tenant": compliant,
            "p99_slo_ms": args.p99_slo_ms,
            "max_shed": args.max_shed,
            "generate_ratio": args.generate_ratio,
            "stages": stages,
        },
    }
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print("loadgen %s model=%s mix=%s" % (base, model,
                                          ",".join(mix.names)))
    print("%-8s %-10s %8s %8s %8s %9s %9s"
          % ("rps", "tenant", "ok", "shed", "errors",
             "p99_ms", "goodput"))
    for stage in stages:
        for name in mix.names:
            s = stage["tenants"][name]
            print("%-8g %-10s %8d %8d %8d %9s %9s"
                  % (stage["offered_rps"], name, s["ok"], s["shed"],
                     s["errors"],
                     "-" if s["p99_ms"] is None else s["p99_ms"],
                     s["goodput_rps"]))
    print("routed_capacity_rps_at_p99_slo %g  (tenant %s, "
          "p99 <= %gms, shed <= %g%%)"
          % (capacity, compliant, args.p99_slo_ms,
             args.max_shed * 100.0))
    return 0


if __name__ == "__main__":
    sys.exit(loadgen_main())
