"""Closed-loop continual training (ISSUE 16).

The loop this module closes, end to end::

    ingest source ──HTTP──> ContinualStreamLoader (bounded prefetch,
         │                  per-slave shards; veles/loader/stream.py)
         │                        │ rounds of Workflow.run()
         │                        v
         │                  snapshotter `current` slot on the
         │                  wall-clock gate — MANIFEST stamped with
         │                  the model-health verdict AND `ingest_wall`
         │                        │
         │                        v snapshot store
         │                  serving replicas (registry refresh-poll;
         │                  diverged blobs skipped, logged, counted)
         │                        │
         │                        v
         └─ staleness ──── router rolling refresh: drain -> reload ->
            SLO closes        /readyz -> re-admit, one replica at a
            the loop          time (veles/router.py)

**Staleness** is the loop's SLO: ``veles_staleness_seconds{point=…}``
measures *now minus the ingest wall time of the newest sample behind
what that point runs* — the trainer's live ingest clock, or the
``ingest_wall`` stamped into the MANIFEST a serving replica loaded.
A wedged ingest source, a crashed trainer, a refused (diverged)
checkpoint or a stuck rollout all surface the same way: the gauge
climbs, the burn-rate alert fires, ``/readyz`` names the objective.

This module owns the shared vocabulary: the ingest clock the
snapshotter stamps from, the staleness gauge family every point
publishes into, the SLO installer, the HTTP ingest transport the
chaos tests brown out, and the ``--continual`` round loop.
"""

import io
import json
import threading
import time
import urllib.request

import numpy

from veles import telemetry
from veles.loader.stream import StreamSource

#: THE staleness gauge family — every observation point (trainer,
#: serving replicas, router fleet view) publishes one labelled child;
#: fleet summaries take the MAX over children (worst point), never
#: the sum
STALENESS_FAMILY = "veles_staleness_seconds"

_clock_lock = threading.Lock()
_ingest_clock = None


def register_ingest_clock(fn):
    """Register the process-wide ingest clock: a callable returning
    the wall time of the newest sample the trainer has ingested (or
    None/0 before the first one). The snapshotter's
    ``health_stamp_meta`` reads it so every checkpoint writer stamps
    ``ingest_wall`` into the MANIFEST."""
    global _ingest_clock
    with _clock_lock:
        _ingest_clock = fn


def ingest_wall():
    """Wall time of the newest ingested sample, or None when no clock
    is registered / nothing has been ingested yet."""
    with _clock_lock:
        fn = _ingest_clock
    if fn is None:
        return None
    try:
        wall = fn()
    except Exception:
        return None
    return float(wall) if wall else None


def staleness_gauge():
    return telemetry.gauge(
        STALENESS_FAMILY,
        "End-to-end staleness: now minus the ingest wall time of the "
        "newest sample behind this observation point (0 until the "
        "point has an ingest clock)", ("point",))


def staleness_of(wall):
    """Seconds of staleness for an ingest wall time (0 when unknown:
    a point that never saw data has no loop to be behind)."""
    if not wall:
        return 0.0
    return max(0.0, time.time() - float(wall))


def install_point_gauge(point, wall_fn):
    """Publish ``veles_staleness_seconds{point=...}`` evaluated at
    scrape time from ``wall_fn`` (-> ingest wall or None)."""
    staleness_gauge().labels(point).set_function(
        lambda: staleness_of(wall_fn()))


def install_staleness_slo(threshold=120.0, point="trainer",
                          monitor=None, target=0.9, fast_window=60.0,
                          slow_window=300.0, burn_threshold=1.0):
    """Arm the staleness burn-rate objective on the health plane:
    samples where the point's staleness exceeds ``threshold`` burn
    error budget; a stalled loop flips ``/readyz`` naming
    ``staleness``. -> 1 when installed, 0 when already armed."""
    from veles import health
    monitor = monitor if monitor is not None else health.get_monitor()
    name = "staleness" if point == "trainer" else "staleness_%s" % point
    if name in monitor._slo_names:
        return 0
    monitor.add_slo({
        "name": name,
        "kind": "threshold",
        "series": '%s{point="%s"}' % (STALENESS_FAMILY, point),
        "op": "<=",
        "threshold": float(threshold),
        "target": float(target),
        "fast_window": float(fast_window),
        "slow_window": float(slow_window),
        "burn_threshold": float(burn_threshold),
    })
    return 1


# -- HTTP ingest transport ---------------------------------------------


def stream_handler(source):
    """A :class:`veles.reactor.HttpServer` handler serving a
    :class:`StreamSource` — the wire the chaos tests put a
    :class:`~veles.chaos.BrownoutProxy` in front of:

    * ``GET /stream/spec`` -> ``{"spec": {name: [shape, dtype]}}``
    * ``GET /stream/fetch?start=N&count=M`` -> npz bytes
    """
    from urllib.parse import parse_qs, urlparse

    def handler(request):
        url = urlparse(request.path)
        if url.path == "/stream/spec":
            request.reply_json(200, {"spec": {
                name: [list(shape), numpy.dtype(dtype).str]
                for name, (shape, dtype) in source.spec().items()}})
            return
        if url.path == "/stream/fetch":
            q = parse_qs(url.query)
            try:
                start = int(q["start"][0])
                count = int(q["count"][0])
            except (KeyError, ValueError, IndexError):
                request.reply_json(
                    400, {"error": "need start=N&count=M"})
                return
            # fetch may block on upstream: never on the reactor loop
            def produce():
                arrays = source.fetch(start, count)
                buf = io.BytesIO()
                numpy.savez(buf, **arrays)
                request.reply(200, buf.getvalue(),
                              ctype="application/octet-stream")
            request.defer(produce)
            return
        request.reply_json(404, {"error": "no route %s" % url.path})

    return handler


class HttpStreamSource(StreamSource):
    """Seekable source over the :func:`stream_handler` wire. Fetch
    failures PROPAGATE — the loader's producer thread owns the
    retry-forever policy, and a black-holed connection surfaces here
    as a socket timeout (the staleness-SLO stall, not a crash)."""

    def __init__(self, base, timeout=5.0):
        self.base = str(base).rstrip("/")
        self.timeout = float(timeout)
        self._spec = None

    def spec(self):
        if self._spec is None:
            with urllib.request.urlopen(
                    self.base + "/stream/spec",
                    timeout=self.timeout) as resp:
                doc = json.load(resp)
            self._spec = {
                name: (tuple(shape), numpy.dtype(dtype))
                for name, (shape, dtype) in doc["spec"].items()}
        return self._spec

    def fetch(self, start, count):
        url = "%s/stream/fetch?start=%d&count=%d" % (
            self.base, int(start), int(count))
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            raw = resp.read()
        with numpy.load(io.BytesIO(raw), allow_pickle=False) as npz:
            return {name: npz[name] for name in npz.files}


# -- the trainer round loop --------------------------------------------


def continual_loop(workflow, rounds=None, launcher=None, logger=None):
    """Drive ``workflow.run()`` indefinitely (or for ``rounds``
    rounds), re-opening the decision's stop gate between rounds.

    Wiring per call: the loader's ingest clock becomes the process
    ingest clock (so interval checkpoints carry ``ingest_wall``), the
    trainer staleness gauge is published, and the no-improvement stop
    is disarmed — patience is meaningless against a shifting stream;
    only the interrupt/preemption path (or ``rounds``) ends the run.
    The durability layer is untouched: the snapshotter's wall-clock
    gate keeps emitting verified ``current``-slot checkpoints inside
    each round. -> number of completed rounds.
    """
    log = logger if logger is not None else workflow
    decision = getattr(workflow, "decision", None)
    if decision is None:
        raise ValueError(
            "--continual needs a workflow with a decision unit "
            "(the round boundary is decision.max_epochs)")
    loader = getattr(workflow, "loader", None)
    if loader is not None and hasattr(loader, "last_ingest_wall"):
        register_ingest_clock(
            lambda: getattr(loader, "last_ingest_wall", 0.0))
    install_point_gauge("trainer", ingest_wall)
    round_epochs = max(1, int(decision.max_epochs or 1)
                       - int(decision.epoch_number))
    decision.fail_iterations = float("inf")
    tele_rounds = telemetry.counter(
        "veles_continual_rounds_total",
        "Completed continual-training rounds", ("workflow",)).labels(
            workflow.name)
    tele_round = telemetry.gauge(
        "veles_continual_round",
        "Rounds completed by this continual run", ("workflow",)).labels(
            workflow.name)
    log.info("continual mode: %s rounds of %d epoch(s) each",
             "endless" if rounds is None else str(rounds), round_epochs)
    done = 0
    while rounds is None or done < rounds:
        if launcher is not None and (launcher.interrupted
                                     or launcher.preempted):
            break
        decision.complete << False
        decision.max_epochs = int(decision.epoch_number) + round_epochs
        workflow.run()
        if workflow._stopped and not bool(decision.complete):
            # stop() landed mid-round (interrupt/preemption): the
            # round did not finish — don't count it
            break
        done += 1
        tele_rounds.inc()
        tele_round.set(done)
        telemetry.record_event(
            "continual_round", workflow=workflow.name, round=done,
            epoch=int(decision.epoch_number),
            ingest_wall=ingest_wall())
    log.info("continual run ended after %d round(s) (epoch %d)",
             done, int(decision.epoch_number))
    return done
