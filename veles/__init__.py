"""znicz-tpu: a TPU-native rebuild of the VELES/Znicz platform.

Package layout mirrors the reference's layering (SURVEY.md §1):

* ``veles.*``           — core runtime (units, workflow, config, memory,
  backends, prng, loader, distribution, launcher, snapshotter).
* ``veles.parallel``    — device mesh / sharding / collectives (the ICI
  replacement for the reference's ZeroMQ master↔slave layer).
* ``veles.znicz_tpu``   — the neural-network plugin: ops, unit pairs,
  StandardWorkflow, models/samples.
"""

__version__ = "0.1.0"

from veles.config import root, Config, Tune  # noqa: F401
from veles.mutable import Bool               # noqa: F401
from veles.units import Unit, TrivialUnit    # noqa: F401
from veles.workflow import Workflow          # noqa: F401
