"""Global mutable configuration tree.

TPU-native re-design of the VELES config system (reference:
``veles/config.py`` [U] per SURVEY.md §0 — reference mount empty, upstream
layout reconstructed; see SURVEY.md §2.1 "Config").

Semantics preserved from the reference:

* a process-global tree ``root`` with attribute access (``root.mnist.lr``);
* sub-trees auto-vivify on attribute access, so python config files can
  freely write ``root.my_workflow.decision.max_epochs = 3``;
* ``Config.update(dict)`` deep-merges nested dicts;
* CLI dot-path overrides (``root.a.b=3`` with python-literal values);
* ``Tune(default, min, max)`` wrappers marking leaves searchable by the
  genetic optimizer (SURVEY.md §2.7 "Genetics");
* pretty-printing of the effective config.
"""

import ast
from typing import Any, Dict, Iterator, Tuple


class Tune:
    """A config leaf marked as tunable by the genetic optimizer.

    Behaves like its ``default`` value for normal reads (via
    :meth:`Config.get` resolution), while carrying the search interval.
    Mirrors ``veles.genetics.Tune`` [U].
    """

    __slots__ = ("default", "min_value", "max_value", "discrete")

    def __init__(self, default, min_value, max_value, discrete=None):
        self.default = default
        self.min_value = min_value
        self.max_value = max_value
        # Discrete if endpoints are ints and default is an int.
        if discrete is None:
            discrete = all(
                isinstance(v, int) and not isinstance(v, bool)
                for v in (default, min_value, max_value))
        self.discrete = discrete

    def clip(self, value):
        value = max(self.min_value, min(self.max_value, value))
        if self.discrete:
            value = int(round(value))
        return value

    def __repr__(self):
        return ("Tune(%r, %r, %r)"
                % (self.default, self.min_value, self.max_value))


def _resolve(value):
    return value.default if isinstance(value, Tune) else value


class Config:
    """A node in the global config tree.

    Attribute reads on missing names auto-vivify child :class:`Config`
    nodes (so config files can assign deep paths without boilerplate);
    attribute writes store leaves verbatim (including :class:`Tune`).
    """

    def __init__(self, path: str):
        # Use object.__setattr__ to dodge our own __setattr__.
        object.__setattr__(self, "_path", path)
        object.__setattr__(self, "_items", {})

    # -- tree access --------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        items = object.__getattribute__(self, "_items")
        if name not in items:
            child = Config("%s.%s" % (self._path, name))
            items[name] = child
        return _resolve(items[name])

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        if isinstance(value, dict):
            node = Config("%s.%s" % (self._path, name))
            node.update(value)
            value = node
        object.__getattribute__(self, "_items")[name] = value

    def __delattr__(self, name: str) -> None:
        object.__getattribute__(self, "_items").pop(name, None)

    def __contains__(self, name: str) -> bool:
        return name in object.__getattribute__(self, "_items")

    def get(self, name: str, default: Any = None) -> Any:
        items = object.__getattribute__(self, "_items")
        if name in items:
            return _resolve(items[name])
        return default

    def raw(self, name: str) -> Any:
        """Return the stored leaf without Tune resolution."""
        return object.__getattribute__(self, "_items")[name]

    # -- bulk update --------------------------------------------------

    def update(self, tree: Dict[str, Any]) -> "Config":
        """Deep-merge a nested dict into this node (reference
        ``Config.update`` [U])."""
        for key, value in tree.items():
            if isinstance(value, dict):
                child = getattr(self, key)
                if not isinstance(child, Config):
                    child = Config("%s.%s" % (self._path, key))
                    object.__getattribute__(self, "_items")[key] = child
                child.update(value)
            else:
                setattr(self, key, value)
        return self

    # -- CLI dot-path overrides --------------------------------------

    def apply_override(self, assignment: str) -> None:
        """Apply one ``a.b.c=value`` override (value is a python literal;
        bare words fall back to strings). The leading ``root.`` is
        optional, matching ``velescli.py`` behaviour [U]."""
        path, _, literal = assignment.partition("=")
        if not _:
            raise ValueError("override must look like path=value: %r"
                             % assignment)
        parts = path.strip().split(".")
        if parts and parts[0] in ("root", self._path.split(".")[0]):
            parts = parts[1:]
        if not parts or any(not p.isidentifier() for p in parts):
            raise ValueError("bad override path in %r" % assignment)
        node = self
        for part in parts[:-1]:
            nxt = getattr(node, part)
            if not isinstance(nxt, Config):
                nxt = Config("%s.%s" % (node._path, part))
                object.__getattribute__(node, "_items")[part] = nxt
            node = nxt
        try:
            value = ast.literal_eval(literal.strip())
        except (ValueError, SyntaxError):
            value = literal.strip()
        setattr(node, parts[-1], value)

    # -- introspection ------------------------------------------------

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(object.__getattribute__(self, "_items").items())

    def flatten(self, prefix: str = "") -> Dict[str, Any]:
        out = {}
        for key, value in self.items():
            full = "%s.%s" % (prefix, key) if prefix else key
            if isinstance(value, Config):
                out.update(value.flatten(full))
            else:
                out[full] = value
        return out

    def tunables(self, prefix: str = "") -> Dict[str, Tune]:
        """All Tune leaves under this node, keyed by dotted path."""
        return {k: v for k, v in self.flatten(prefix).items()
                if isinstance(v, Tune)}

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for key, value in self.items():
            out[key] = value.to_dict() if isinstance(value, Config) \
                else _resolve(value)
        return out

    def print_config(self, indent: int = 0, stream=None) -> str:
        lines = []

        def rec(node, depth):
            for key, value in sorted(node.items()):
                pad = "  " * depth
                if isinstance(value, Config):
                    lines.append("%s%s:" % (pad, key))
                    rec(value, depth + 1)
                else:
                    lines.append("%s%s: %r" % (pad, key, value))

        rec(self, indent)
        text = "\n".join(lines)
        if stream is not None:
            stream.write(text + "\n")
        return text

    def __repr__(self):
        return "<Config %s: %d item(s)>" % (
            self._path, len(object.__getattribute__(self, "_items")))


#: The process-global config tree every workflow/config file mutates,
#: mirroring ``veles.config.root`` [U].
root = Config("root")

# Defaults under root.common, as in the reference (cache/data dirs,
# backend selection; SURVEY.md §2.1).
root.common.update({
    "dirs": {
        "cache": "/tmp/znicz_tpu/cache",
        "datasets": "/tmp/znicz_tpu/datasets",
        "snapshots": "/tmp/znicz_tpu/snapshots",
    },
    "engine": {
        "backend": "xla",       # "xla" | "numpy"
        "precision": "float32",  # oracle dtype; TPU path uses bfloat16 matmuls
    },
})
