"""Device backends.

Re-design of ``veles/backends.py`` [U] (SURVEY.md §2.1 "Device
backends"). The reference enumerated OpenCL/CUDA devices and kept a
per-device tuned BLOCK_SIZE database for its hand-written kernels. On
TPU, XLA owns tiling/autotuning, so a Device here is much thinner:

* :class:`NumpyDevice` — the oracle backend; all ``numpy_run`` paths.
* :class:`XLADevice` — wraps the jax device set (TPU chips, or CPU when
  ``JAX_PLATFORMS=cpu``), owns the default :class:`jax.sharding.Mesh`,
  precision policy (bfloat16 matmuls on the MXU, float32 params), and
  the compile cache directory (the reference cached compiled kernels on
  disk; jax's persistent compilation cache is the analogue).

Device selection mirrors ``velescli -d``: ``"numpy"`` forces the oracle,
``"xla"`` / ``"tpu"`` / ``"cpu"`` pick jax platforms.
"""

import os

import numpy

from veles.config import root
from veles.logger import Logger


class Device(Logger):
    backend_name = "abstract"

    #: True when jax is the execution engine.
    is_xla = False

    def __init__(self):
        self.name = type(self).__name__

    @property
    def exists(self):
        return True

    def __repr__(self):
        return "<%s>" % self.backend_name


class NumpyDevice(Device):
    """Pure-numpy oracle backend (reference ``NumpyDevice`` [U])."""

    backend_name = "numpy"

    def __init__(self, dtype=numpy.float32):
        super().__init__()
        self.dtype = numpy.dtype(dtype)


class XLADevice(Device):
    """JAX/XLA execution: TPU when available, CPU otherwise.

    The whole forward/backward/update cycle compiles into one program
    (SURVEY.md §7 design stance) so, unlike the reference's per-kernel
    device state, this object mostly carries policy: dtypes, the mesh,
    and donation settings.
    """

    backend_name = "xla"
    is_xla = True

    def __init__(self, platform=None, mesh=None,
                 compute_dtype=None, param_dtype=None):
        super().__init__()
        import jax
        self._jax = jax
        if platform:
            devices = jax.devices(platform)
        else:
            devices = jax.devices()
        self.jax_devices = devices
        self.platform = devices[0].platform
        self.mesh = mesh  # set up lazily / by veles.parallel
        # bfloat16 matmuls feed the MXU at full rate; params stay f32.
        # "axon" is a TPU chip behind the dev tunnel — same MXU.
        # Overridable from config (root.common.engine.compute_dtype =
        # "float32"/"bfloat16"): measured on v5e, bf16 wins big on the
        # conv stack (AlexNet +21%) but costs ~4% on the transformer
        # LM (cast traffic around the matmuls) — workloads differ.
        import jax.numpy as jnp

        def policy_dtype(cfg_key, allowed):
            """Config-overridable dtype with the TPU-first default:
            bf16 on a TPU (incl. the tunnel's "axon" platform — same
            MXU), f32 elsewhere (keeps the CPU parity suite exact)."""
            cfg_dt = root.common.engine.get(cfg_key)
            if cfg_dt:
                if cfg_dt not in allowed:
                    raise ValueError(
                        "root.common.engine.%s must be one of %s, "
                        "got %r" % (cfg_key, allowed, cfg_dt))
                return getattr(jnp, cfg_dt)
            return (jnp.bfloat16 if self.platform in ("tpu", "axon")
                    else jnp.float32)

        self.compute_dtype = compute_dtype or policy_dtype(
            "compute_dtype", ("float32", "bfloat16", "float16"))
        self.param_dtype = param_dtype or jnp.float32
        # Mixed-precision ACTIVATION policy (root.common.engine.amp =
        # "bfloat16"/"float32"): tensors flowing BETWEEN units (outputs
        # and err flows) are stored in this dtype; master weights and
        # solver state stay in param_dtype (f32), loss/softmax/stat
        # reductions compute in f32. On a v5e the f32 activation flow
        # was the single largest cost of the AlexNet step (LRN, pooling
        # scatter and bias-sum fusions are HBM-bandwidth-bound); bf16
        # halves it.
        self.act_dtype = policy_dtype("amp", ("float32", "bfloat16"))
        cache_dir = os.path.join(root.common.dirs.cache, "xla")
        os.makedirs(cache_dir, exist_ok=True)
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception:  # pragma: no cover - older jax
            pass

    @property
    def device_count(self):
        return len(self.jax_devices)

    def __repr__(self):
        return "<xla:%s x%d>" % (self.platform, self.device_count)


def get_device(spec=None) -> Device:
    """Build a Device from a CLI-ish spec.

    ``None`` → config default (``root.common.engine.backend``);
    ``"numpy"`` → oracle; ``"xla"`` → default jax platform;
    ``"tpu"``/``"cpu"`` → that jax platform.
    """
    if isinstance(spec, Device):
        return spec
    spec = spec or root.common.engine.backend
    if spec == "numpy":
        return NumpyDevice()
    if spec in ("xla", None):
        return XLADevice()
    return XLADevice(platform=spec)
