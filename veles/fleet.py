"""Fleet aggregator + ``velescli top``: one view over N processes.

The health plane (``veles/health.py``) gives every process probes,
metrics history and SLO alerts; this module is the CLUSTER side — a
scraper that polls N targets' ``/healthz`` + ``/readyz`` +
``/metrics`` + ``/status.json`` + ``/metrics.json`` surfaces, merges
the per-slave timing the master already reports in
``MasterServer.status()``, and renders either a live refreshing
terminal dashboard (``velescli top URL...``) or one machine-readable
snapshot (``--json``) — the artifact a router tier or autoscaler
consumes (ROADMAP item 2).

Every fetch is best-effort per endpoint: a serving frontend has no
``/status.json``, an old process has no ``/readyz`` — missing
surfaces degrade the row, never kill the scrape. Non-200 probe
answers (a 503 ``/readyz`` carries the reason JSON) are read, not
treated as transport errors.
"""

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

#: scrape fan-out cap: enough to cover a rack of replicas in one
#: wave without spawning a thread herd for a 200-target fleet
MAX_SCRAPE_WORKERS = 16

#: one Prometheus exposition sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


_ESCAPE_RE = re.compile(r"\\(.)")


def _unescape(value):
    # ONE left-to-right pass: sequential str.replace mis-decodes
    # values like 'C:\\\\new' (an escaped backslash followed by a
    # literal n must not become a newline)
    return _ESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value)


def parse_prometheus(text):
    """Prometheus text exposition -> ``{(name, label_items): value}``
    with ``label_items`` a sorted tuple of (key, value) pairs.
    Comment/HELP/TYPE lines and malformed rows are skipped — a scrape
    must survive whatever a half-written exposition contains."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, value = m.groups()
        try:
            v = float(value)
        except ValueError:
            continue
        items = tuple(sorted(
            (k, _unescape(raw))
            for k, raw in _LABEL_RE.findall(labels or "")))
        out[(name, items)] = v
    return out


def metric_total(metrics, name, **match):
    """Sum of ``name`` samples whose labels contain every ``match``
    item (the scrape-side sibling of ``Registry.counter_total``)."""
    want = {(k, str(v)) for k, v in match.items()}
    total, hit = 0.0, False
    for (n, items), v in metrics.items():
        if n == name and want <= set(items):
            total += v
            hit = True
    return total if hit else None


def metric_max(metrics, name, **match):
    """Max over ``name``'s matching children — for staleness-style
    gauges where the fleet number is the WORST point (summing
    staleness across points would fabricate a worse loop than
    exists)."""
    want = {(k, str(v)) for k, v in match.items()}
    best = None
    for (n, items), v in metrics.items():
        if n == name and want <= set(items):
            best = v if best is None else max(best, v)
    return best


def metric_by_label(metrics, name, label):
    """``{label_value: sum}`` over ``name``'s children grouped by one
    label, or None when the family is absent. Children WITHOUT the
    label (an old exposition predating it) contribute nothing — the
    caller sees an empty dict, not fabricated zeros."""
    out, hit = {}, False
    for (n, items), v in metrics.items():
        if n != name:
            continue
        hit = True
        value = dict(items).get(label)
        if value is not None:
            out[value] = out.get(value, 0.0) + v
    return out if hit else None


def histogram_quantile(metrics, name, q, **match):
    """PromQL-style quantile over ``name``'s cumulative ``_bucket``
    samples (summed across matching children), with linear
    interpolation inside the winning bucket; -> seconds, or None
    when the histogram is absent or empty (a pre-traffic replica
    must read as 'unknown', never 'instant')."""
    want = {(k, str(v)) for k, v in match.items()}
    buckets = {}
    for (n, items), v in metrics.items():
        if n != name + "_bucket":
            continue
        d = dict(items)
        le = d.pop("le", None)
        if le is None or not want <= set(d.items()):
            continue
        try:
            bound = (float("inf") if le == "+Inf" else float(le))
        except ValueError:
            continue
        buckets[bound] = buckets.get(bound, 0.0) + v
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets[bounds[-1]]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for b in bounds:
        cum = buckets[b]
        if cum >= rank:
            if b == float("inf") or cum == prev_cum:
                return prev_bound if b == float("inf") else b
            return prev_bound + (b - prev_bound) \
                * (rank - prev_cum) / (cum - prev_cum)
        prev_bound, prev_cum = b, cum
    return prev_bound


def _fetch(url, timeout):
    """(status_code, body_bytes) — HTTP error codes are ANSWERS here
    (a 503 /readyz carries the reason payload), only transport
    failures raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


def _fetch_json(url, timeout):
    code, body = _fetch(url, timeout)
    return code, json.loads(body)


def scrape_target(base, timeout=5.0, total=None, extras=True):
    """Poll one process's health surfaces; -> its merged row dict.
    ``base`` is ``http://host:port`` of a web-status dashboard, a
    serving frontend or a router.

    ``total`` caps the WHOLE scrape of this target (default
    ``2 x timeout``): every individual fetch waits at most the
    remaining budget, and once it is spent the later surfaces are
    skipped (``row["partial"] = True``) instead of queueing behind a
    wedged peer — the bound a router control loop on this path needs
    (ISSUE 13). ``extras=False`` skips the heavyweight optional
    surfaces (``/metrics.json``, ``/status.json``, critical path,
    router status) for tight control-loop scrapes."""
    base = base.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    deadline = time.monotonic() + (2.0 * timeout if total is None
                                   else max(float(total), 0.05))

    def budget():
        """Remaining per-fetch wait: the request timeout, clamped to
        the target's whole-scrape budget (<= 0 once it is spent)."""
        return min(timeout, deadline - time.monotonic())

    row = {"url": base, "reachable": False}

    def spent():
        """True (and the row marked partial) once the whole-scrape
        budget is gone — 'slow target, scrape truncated' must stay
        distinguishable from 'target has no such surface'."""
        if budget() <= 0:
            row["partial"] = True
            return True
        return False

    try:
        code, body = _fetch(base + "/healthz", max(budget(), 0.05))
    except Exception as exc:
        row["error"] = "%s: %s" % (type(exc).__name__, exc)
        return row
    # ANY HTTP answer proves the process is up — a pre-health-plane
    # dashboard 404s /healthz with a text body, and must degrade the
    # row (live=False, no probe doc), never read as DOWN
    row["reachable"] = True
    row["live"] = code == 200
    try:
        row["healthz"] = json.loads(body)
    except ValueError:
        row["healthz"] = None
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        code, doc = _fetch_json(base + "/readyz", budget())
        row["ready"] = code == 200
        row["reasons"] = list(doc.get("reasons", ()))
        row["checks"] = doc.get("checks", {})
        row["slos"] = doc.get("slos", {})
    except Exception:
        spent()      # a fetch that DIED on the budget marks partial
        row["ready"] = None          # pre-health-plane process
        row["reasons"] = []
        row["slos"] = {}
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        _, body = _fetch(base + "/metrics", budget())
        metrics = parse_prometheus(body.decode("utf-8", "replace"))
    except Exception:
        # mark truncation when the budget died MID-fetch too: a
        # consumer must never read "metrics absent" (gauges reset)
        # for what was really "metrics unreadable in budget"
        spent()
        metrics = {}
    row["firing"] = sorted(
        dict(items).get("objective", "?")
        for (name, items), v in metrics.items()
        if name == "veles_slo_alert_firing" and v > 0)
    summary = {}
    tx = metric_total(metrics, "veles_wire_bytes_total",
                      direction="tx")
    if tx is not None:
        summary["wire_tx_bytes"] = tx
    # reactor loop lag (ISSUE 9): the "is the shared loop healthy"
    # number — sustained lag means a callback is blocking the wire
    # plane and every probe behind it
    lag = metric_total(metrics, "veles_reactor_loop_lag_seconds")
    if lag is not None:
        summary["reactor_lag_s"] = lag
    # memory accounting (ISSUE 10): host RSS rendered next to the
    # loop lag — absent on pre-PR-10 targets, which must only degrade
    # the row
    rss = metric_total(metrics, "veles_host_rss_bytes")
    if rss is not None:
        summary["host_rss_bytes"] = rss
    fds = metric_total(metrics, "veles_host_open_fds")
    if fds is not None:
        summary["host_open_fds"] = fds
    for key, name in (("serving_requests",
                       "veles_serving_requests_total"),
                      ("serving_rejected",
                       "veles_serving_rejected_total"),
                      ("serving_queue_rows",
                       "veles_serving_queue_rows"),
                      # decode plane (ISSUE 11): cumulative tokens +
                      # KV occupancy — absent on pre-PR-11 targets,
                      # which must only degrade the row
                      ("generated_tokens",
                       "veles_serving_generated_tokens_total"),
                      ("kv_slots_in_use",
                       "veles_serving_kv_slots_in_use"),
                      ("kv_pool_slots",
                       "veles_serving_kv_pool_slots"),
                      ("cluster_slaves", "veles_cluster_slaves"),
                      ("cluster_faults",
                       "veles_cluster_faults_total")):
        v = metric_total(metrics, name)
        if v is not None:
            summary[key] = v
    # continual loop (ISSUE 16): end-to-end staleness and the served
    # checkpoint's wall — MAX over label children, and absent on
    # pre-PR-16 targets, which must only degrade the row
    stale = metric_max(metrics, "veles_staleness_seconds")
    if stale is not None:
        summary["staleness_seconds"] = stale
    wall = metric_max(metrics,
                      "veles_serving_checkpoint_wall_seconds")
    if wall is not None:
        summary["serving_ckpt_wall"] = wall
    # per-request serving p99 out of the Prometheus histogram buckets
    # (ISSUE 18): what the router's latency routing policy weighs —
    # absent (None) on pre-traffic or pre-histogram targets
    p99 = histogram_quantile(metrics,
                             "veles_serving_latency_seconds", 0.99)
    if p99 is not None:
        summary["serving_p99_s"] = round(p99, 6)
    # per-tenant attribution (ISSUE 18): requests/rejections on a
    # serving replica, routed requests on a router — families (or
    # their tenant label) absent on pre-PR-18 targets, which must
    # only degrade the row
    by_tenant = {}
    for key, name in (("requests",
                       "veles_serving_tenant_requests_total"),
                      ("rejected", "veles_serving_rejected_total"),
                      ("tokens", "veles_serving_tenant_tokens_total"),
                      ("routed", "veles_router_requests_total")):
        grouped = metric_by_label(metrics, name, "tenant")
        for tenant, v in (grouped or {}).items():
            by_tenant.setdefault(tenant, {})[key] = v
    if by_tenant:
        summary["tenants"] = by_tenant
    row["metrics"] = summary
    if not extras:
        # control-loop scrapes target serving replicas: skip the
        # optional surfaces INCLUDING /router/status (a guaranteed
        # 404 round trip per replica per tick otherwise)
        row["role"] = "process"
        return row
    # the router tier (ISSUE 13): a routing process answers
    # /router/status with its per-backend control-plane state
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        code, doc = _fetch_json(base + "/router/status", budget())
        if code == 200 and isinstance(doc, dict) \
                and isinstance(doc.get("backends"), list):
            row["router"] = doc
    except Exception:
        pass
    # serving side: the per-model JSON view (rps, p99, queue, shed)
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        code, doc = _fetch_json(base + "/metrics.json", budget())
        if code == 200 and isinstance(doc, dict) \
                and isinstance(doc.get("models"), dict):
            row["serving"] = doc["models"]
    except Exception:
        pass
    # training side: the dashboard's status providers — the master's
    # row carries cluster topology + per-slave last-job timing
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        code, doc = _fetch_json(base + "/status.json", budget())
        if code == 200 and isinstance(doc, dict):
            row["status"] = doc
            for st in doc.values():
                if isinstance(st, dict) and "slaves" in st:
                    row["master"] = {
                        "epoch": st.get("epoch"),
                        "max_epochs": st.get("max_epochs"),
                        "n_slaves": st.get("n_slaves"),
                        "complete": st.get("complete"),
                        "faults": st.get("faults"),
                        "slaves": st.get("slaves"),
                    }
    except Exception:
        pass
    # critical-path breakdown (ISSUE 10): where the step/request time
    # goes, per leg — a 404 from a pre-PR-10 target degrades the row,
    # never errors it
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        code, doc = _fetch_json(
            base + "/debug/critical_path?window=120", budget())
        if code == 200 and isinstance(doc, dict) \
                and ("train" in doc or "serving" in doc):
            row["critical_path"] = doc
    except Exception:
        pass
    # model health (ISSUE 15): the training-dynamics verdict +
    # loss/grad-norm snapshot — a 404/garbled answer from a target
    # that predates /debug/model degrades the row, never errors it
    try:
        if spent():
            raise TimeoutError("scrape budget spent")
        code, doc = _fetch_json(base + "/debug/model", budget())
        if code == 200 and isinstance(doc, dict) \
                and "verdict" in doc:
            row["model"] = doc
    except Exception:
        pass
    row["role"] = "router" if "router" in row else (
        "master" if "master" in row else (
            "serving" if "serving" in row else "process"))
    return row


def scrape_targets(targets, timeout=5.0, total=None, extras=True,
                   workers=None, pool=None):
    """Scrape every target CONCURRENTLY (thread-pool fan-out, one
    row per target in input order). With the per-target ``total``
    budget inside :func:`scrape_target` this bounds the whole wave
    by the slowest single target instead of the sum — one wedged
    replica used to stall every ``velescli top`` refresh behind it,
    which is fatal for a router control loop on the same path
    (ISSUE 13 satellite). A periodic caller (the router's control
    loop) passes its own long-lived ``pool`` instead of paying
    thread churn every tick."""
    targets = list(targets)
    if not targets:
        return []

    def one(t):
        return scrape_target(t, timeout=timeout, total=total,
                             extras=extras)

    if pool is not None:
        return list(pool.map(one, targets))
    workers = workers or min(len(targets), MAX_SCRAPE_WORKERS)
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="fleet-scrape") as own:
        return list(own.map(one, targets))


def fleet_snapshot(targets, timeout=5.0):
    """Scrape every target; -> the merged fleet document (what
    ``velescli top --json`` prints and an autoscaler consumes)."""
    rows = scrape_targets(targets, timeout=timeout)
    firing = sorted({name for r in rows
                     for name in r.get("firing", ())})
    degraded = sorted(
        r["url"] for r in rows
        if not r.get("reachable") or r.get("ready") is False)
    return {
        "ts": round(time.time(), 3),
        "targets": rows,
        "fleet": {
            "targets": len(rows),
            "reachable": sum(1 for r in rows if r.get("reachable")),
            "ready": sum(1 for r in rows if r.get("ready")),
            "firing_slos": firing,
            "degraded": degraded,
            "slaves": int(sum(
                r.get("metrics", {}).get("cluster_slaves", 0)
                for r in rows)),
        },
    }


# -- rendering ----------------------------------------------------------


def _fmt_critical_path(cp):
    """Per-target step/request breakdown lines out of a
    ``/debug/critical_path`` document (ISSUE 10) — empty when the
    target has no such surface or no attributed traces."""
    if not isinstance(cp, dict):
        return []
    out = []
    for side, label, order in (
            ("train", "step", ("dispatch", "wire", "compute",
                               "merge")),
            ("serving", "serve", ("queue", "execute"))):
        doc = cp.get(side)
        if not isinstance(doc, dict) or not doc.get("jobs"):
            continue
        legs = doc.get("legs") or {}
        parts = [
            "%s %d%%" % (leg,
                         round(100.0 * legs[leg].get("fraction", 0.0)))
            for leg in order if isinstance(legs.get(leg), dict)]
        line = "%s: %s" % (label, " | ".join(parts) or "-")
        straggler = doc.get("straggler")
        if isinstance(straggler, dict) and straggler.get("slave"):
            line += " (straggler slave %s: %s)" \
                % (straggler["slave"], straggler.get("leg", "?"))
        out.append(line)
    return out


def _fmt_ready(row):
    if not row.get("reachable"):
        return "DOWN"
    if row.get("ready") is None:
        return "live"
    return "ready" if row["ready"] else "NOT-READY"


def render_snapshot(snap):
    """The terminal dashboard body for one fleet snapshot."""
    lines = []
    fleet = snap["fleet"]
    lines.append(
        "veles fleet — %d target(s), %d reachable, %d ready, "
        "%d slave(s)%s" % (
            fleet["targets"], fleet["reachable"], fleet["ready"],
            fleet["slaves"],
            "  !! SLO firing: %s" % ", ".join(fleet["firing_slos"])
            if fleet["firing_slos"] else ""))
    lines.append("")
    lines.append("%-28s %-9s %-8s %s"
                 % ("TARGET", "STATE", "ROLE", "DETAIL"))
    for row in snap["targets"]:
        detail = []
        if not row.get("reachable"):
            detail.append(row.get("error", "unreachable"))
        router = row.get("router")
        if isinstance(router, dict):
            backends = router.get("backends") or []
            admitted = sum(1 for b in backends
                           if b.get("state") == "admitted")
            detail.append("router: %d/%d backend(s) admitted"
                          % (admitted, len(backends)))
            bad = ["%s (%s)" % (b.get("url", "?").replace(
                       "http://", ""), b.get("reason") or b.get(
                       "state"))
                   for b in backends
                   if b.get("state") not in ("admitted", None)]
            if bad:
                detail.append("out: " + ", ".join(bad))
            scaler = router.get("autoscaler")
            if isinstance(scaler, dict) and scaler.get("last"):
                last = scaler["last"]
                detail.append("autoscale %s @%s"
                              % (last.get("direction"),
                                 last.get("url", "-")))
            # rolling refresh (ISSUE 16): which replica last rolled
            # to a fresh checkpoint — absent on pre-PR-16 routers,
            # which must only degrade the row
            rolling = router.get("rolling_refresh")
            if isinstance(rolling, dict) \
                    and isinstance(rolling.get("last"), dict):
                last = rolling["last"]
                urls = [b.get("url") for b in backends]
                which = (
                    "replica %d" % urls.index(last.get("replica"))
                    if last.get("replica") in urls
                    else str(last.get("replica", "?")).replace(
                        "http://", ""))
                detail.append("last refresh: %s (%s)"
                              % (which, last.get("outcome", "?")))
        master = row.get("master")
        if master:
            detail.append("epoch %s/%s, %s slave(s)"
                          % (master.get("epoch"),
                             master.get("max_epochs"),
                             master.get("n_slaves")))
            faults = master.get("faults") or {}
            busy = {k: v for k, v in faults.items()
                    if v and k != "joins"}
            if busy:
                detail.append("faults " + ",".join(
                    "%s=%s" % kv for kv in sorted(busy.items())))
        for model, m in sorted((row.get("serving") or {}).items()):
            detail.append(
                "%s v%s: %s rps, p99 %sms, queue %s, shed %s"
                % (model, m.get("version"),
                   m.get("requests_per_sec"),
                   m.get("latency_ms_p99", "-"),
                   m.get("queue_depth"), m.get("shed_total")))
            # decode plane (ISSUE 11): tokens/s + KV occupancy next
            # to the predict figures — one glance per generative
            # model; absent on non-generative / pre-PR-11 targets
            dec = m.get("decode")
            if isinstance(dec, dict):
                detail.append(
                    "%s decode: %s tok/s, kv %s/%s, queue %s"
                    % (model, dec.get("tokens_per_sec"),
                       dec.get("kv_slots_in_use"),
                       dec.get("kv_pool_slots"),
                       dec.get("queue_depth")))
        # model health (ISSUE 15): loss + trend, worst layer grad
        # norm and the divergence verdict in one glance — absent on
        # pre-ISSUE-15 targets or before any observation, which must
        # only degrade the row
        model = row.get("model")
        if isinstance(model, dict) and (
                model.get("loss") is not None
                or model.get("layers")
                or model.get("verdict") not in (None, "healthy")):
            # every scraped field is untrusted (version skew, or a
            # foreign service on that port): type-check before
            # formatting, so a garbled doc degrades this row instead
            # of crashing the whole render
            bits = []
            if isinstance(model.get("loss"), (int, float)):
                bits.append("loss %.5g (%s)"
                            % (model["loss"],
                               model.get("loss_trend", "flat")))
            gns = [d.get("grad_norm")
                   for d in (model.get("layers") or {}).values()
                   if isinstance(d, dict)
                   and isinstance(d.get("grad_norm"), (int, float))]
            if gns:
                bits.append("grad-norm %.3g" % max(gns))
            if isinstance(model.get("rollbacks"), (int, float)) \
                    and model["rollbacks"]:
                bits.append("rollbacks %d" % model["rollbacks"])
            bits.append("verdict %s" % model.get("verdict", "?"))
            detail.append("model: " + ", ".join(bits))
        # per-tenant goodput/shed columns (ISSUE 18): one line per
        # target naming each resolved tenant's request/routed/shed
        # counts — absent on pre-PR-18 targets, which must only
        # degrade the row
        by_tenant = row.get("metrics", {}).get("tenants")
        if isinstance(by_tenant, dict):
            parts = []
            for tenant, d in sorted(by_tenant.items()):
                if not isinstance(d, dict):
                    continue
                bits = []
                if d.get("requests") is not None:
                    bits.append("req %d" % d["requests"])
                if d.get("routed") is not None:
                    bits.append("routed %d" % d["routed"])
                if d.get("tokens"):
                    bits.append("tok %d" % d["tokens"])
                if d.get("rejected"):
                    bits.append("shed %d" % d["rejected"])
                if bits:
                    parts.append("%s: %s" % (tenant, " ".join(bits)))
            if parts:
                detail.append("tenants " + " | ".join(parts))
        # host RSS and reactor lag side by side (ISSUE 10): one glance
        # gives "how much memory, how healthy the loop" per target —
        # either may be absent (pre-PR-9/10 process) without a row
        # error
        health_bits = []
        # the loop SLO (ISSUE 16) leads: "how far behind the stream
        # is what this target runs" — absent on pre-PR-16 targets
        stale = row.get("metrics", {}).get("staleness_seconds")
        if stale is not None:
            health_bits.append("staleness %.0fs" % stale)
        rss = row.get("metrics", {}).get("host_rss_bytes")
        if rss is not None:
            health_bits.append("rss %.1fMB" % (rss / 1048576.0))
        lag = row.get("metrics", {}).get("reactor_lag_s")
        if lag is not None:
            health_bits.append("reactor lag %.1fms" % (lag * 1e3))
        if health_bits:
            detail.append(", ".join(health_bits))
        detail.extend(_fmt_critical_path(row.get("critical_path")))
        if row.get("firing"):
            detail.append("SLO firing: " + ",".join(row["firing"]))
        if row.get("ready") is False:
            detail.extend(row.get("reasons", ()))
        lines.append("%-28s %-9s %-8s %s"
                     % (row["url"].replace("http://", ""),
                        _fmt_ready(row), row.get("role", "-"),
                        "; ".join(str(d) for d in detail) or "-"))
        for sid, srow in sorted(
                ((master or {}).get("slaves") or {}).items()):
            lines.append(
                "%-28s %-9s %-8s jobs %s, rtt %ss, compute %ss, "
                "wire %ss, idle %ss"
                % ("  slave %s (%s)" % (sid, srow.get("name")),
                   "", "", srow.get("jobs"), srow.get("last_rtt_s"),
                   srow.get("last_job_s"), srow.get("last_wire_s"),
                   srow.get("idle_s")))
    return "\n".join(lines)


def top_main(argv=None):
    """``velescli top URL [URL...]`` — live fleet dashboard; with
    ``--json`` print ONE snapshot document and exit (0 when every
    target is reachable, 2 when none is)."""
    p = argparse.ArgumentParser(
        prog="velescli top",
        description="Live cluster dashboard over /healthz + /readyz "
                    "+ /metrics + status surfaces of web-status "
                    "dashboards and serving frontends")
    p.add_argument("targets", nargs="+",
                   help="base URLs (http://host:port) of web-status "
                        "dashboards and/or serving frontends")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds (live mode)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-request HTTP timeout")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable snapshot and "
                        "exit (the autoscaler/router artifact)")
    p.add_argument("--once", action="store_true",
                   help="render one dashboard frame and exit")
    args = p.parse_args(argv)
    if args.json or args.once:
        snap = fleet_snapshot(args.targets, timeout=args.timeout)
        if args.json:
            print(json.dumps(snap, indent=2))
        else:
            print(render_snapshot(snap))
        return 0 if snap["fleet"]["reachable"] else 2
    try:
        while True:
            snap = fleet_snapshot(args.targets, timeout=args.timeout)
            # clear + home, then one frame (same trick real top uses)
            sys.stdout.write("\x1b[2J\x1b[H")
            sys.stdout.write(render_snapshot(snap) + "\n")
            sys.stdout.write(
                "\n[%s] refreshing every %gs — ^C to quit\n"
                % (time.strftime("%H:%M:%S"), args.interval))
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
