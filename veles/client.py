"""Slave client — pulls jobs, runs local iterations, pushes updates.

Re-design of ``veles/client.py`` [U] (SURVEY.md §2.2 "Slave client",
§3.3 call stack): connect + handshake, then loop { request job; apply
per-unit payloads (loader gets minibatch indices, GD units get fresh
weights); run one local iteration; push per-unit updates (weights,
eval counters) }. The compute inside the iteration is whatever the
local device does best — on TPU the fused per-step program.
"""

import socket
import time

from veles.distributable import DistributionRegistry
from veles.loader.base import CLASS_TRAIN
from veles.logger import Logger
from veles.server import send_frame, recv_frame, require_secret_for


class SlaveClient(Logger):
    def __init__(self, workflow, address, name=None):
        self.name = name or "SlaveClient"
        self.workflow = workflow
        self._check_mode()
        host, _, port = str(address).rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        require_secret_for(self.address[0], "slave master")
        self.registry = DistributionRegistry(workflow)
        self.slave_id = None
        self.jobs_done = 0

    def connect(self):
        self.sock = socket.create_connection(self.address, timeout=30)
        send_frame(self.sock, ("hello", self.name))
        kind, slave_id = recv_frame(self.sock)
        assert kind == "welcome"
        self.slave_id = slave_id
        return self

    def _check_mode(self):
        """A slave must serve the indices the MASTER assigns per job;
        fused whole-epoch dispatch owns its own minibatch order, so a
        workflow initialized without ``is_slave = True`` (the Launcher
        sets it before initialize) is rejected LOUDLY. Re-checked per
        job, since initialize() may happen after construction."""
        step = getattr(self.workflow, "xla_step", None)
        if step is not None and (step.scan_mode or step.stream_mode):
            raise ValueError(
                "slave workflow was initialized in fused dispatch "
                "mode; set workflow.is_slave = True before "
                "initialize()")

    def run_one(self):
        """Request + run one job; False when the master says stop."""
        self._check_mode()
        send_frame(self.sock, ("job", self.slave_id))
        resp = recv_frame(self.sock)
        if resp is None or resp[0] == "bye":
            return False
        if resp[0] == "wait":
            time.sleep(0.02)
            return True
        self.registry.apply_job(resp[1])
        self._run_iteration()
        send_frame(self.sock,
                   ("update", self.slave_id, self.registry.generate_update()))
        ok = recv_frame(self.sock)
        self.jobs_done += 1
        return ok is not None

    def _run_iteration(self):
        """One forward/backward/update pass over the minibatch the
        master assigned (already applied into the loader)."""
        wf = self.workflow
        if wf.xla_step is not None:
            # master pushed fresh weights into host Arrays: re-upload,
            # step, and sync back so generate_update ships the result
            wf.xla_step.refresh_device()
            wf.xla_step.run()
            wf.xla_step.sync_host()
        else:
            for u in wf.forwards:
                u.run()
            wf.evaluator.run()
            if wf.loader.minibatch_class == CLASS_TRAIN:
                for gd in reversed(wf.gds):
                    gd.run()

    def run_forever(self):
        self.connect()
        try:
            while self.run_one():
                pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass
        self.info("slave done after %d jobs", self.jobs_done)
        return self.jobs_done
