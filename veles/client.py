"""Slave client — pulls jobs, runs local iterations, pushes updates.

Re-design of ``veles/client.py`` [U] (SURVEY.md §2.2 "Slave client",
§3.3 call stack): connect + handshake, then loop { request job; apply
per-unit payloads (loader gets minibatch indices, GD units get fresh
weights); run one local iteration; push per-unit updates (weights,
eval counters) }. The compute inside the iteration is whatever the
local device does best — on TPU the fused per-step program.

Fault tolerance: the client holds a master-minted lease
``(slave_id, lease_id)`` and tags every request with it. Any
``("stale",)`` response means the master revoked the lease (the slave
was dropped and its work requeued) — the client abandons it and
re-hellos for a fresh one instead of corrupting the average. Any
socket failure, timeout or protocol desync triggers reconnect with
exponential backoff + jitter (capped retries), so ``run_forever``
survives master restarts and flaky networks; a run of successful work
resets the budget. A background heartbeat thread sends ``("ping",)``
every ``ping_interval`` whenever the socket is otherwise idle (both
parked on ``("wait",)`` AND deep in a long local iteration), so the
master's ``slave_timeout`` measures actual silence, not compute time.

Socket sharing discipline (ISSUE 9): the heartbeat thread is
SEND-ONLY. Whole-frame sends are serialized by ``_io_lock`` — a ping
can never interleave bytes mid-frame with an in-flight update send —
and the MAIN thread is the only reader: requests and responses are
FIFO on one TCP stream, so ``_roundtrip`` drains the ``("pong",)``
replies owed to outstanding heartbeat pings (counted under the same
lock) before taking its own response. The old design round-tripped
the ping on the heartbeat thread, which serialized heartbeats behind
whole request/response cycles; send-only pings flow even while a
multi-MB update send is in flight. A ``("stale",)`` answered to a
ping is read by the main loop as its own fencing — the correct
outcome either way, since the lease is equally dead for both frames.
"""

import os
import random
import socket
import threading
import time

from veles import telemetry
from veles.distributable import DistributionRegistry
from veles.loader.base import CLASS_TRAIN
from veles.logger import Logger
from veles.server import send_frame, recv_frame, require_secret_for

#: counter families a slave must NOT push to its master: the master
#: owns these names in its own registry (and in co-located test runs
#: both sides share one registry — echoing them back would manufacture
#: fake slave-labelled cluster series)
_NO_PUSH_PREFIXES = ("veles_cluster_", "veles_master_")

#: PER-PROCESS push token: the counter state a client pushes is the
#: process-wide registry, so the master's dedup baseline must be
#: per-process too — two SlaveClients threading in one process (chaos
#: tests) each push the shared totals, and a per-CLIENT token would
#: absorb them twice. Stable across reconnects/re-hellos by
#: construction. (Per-slave attribution is inherently approximate for
#: co-located clients — they share one registry — but sums stay
#: exact; separate-process slaves keep exact attribution.)
import secrets
_PUSH_TOKEN = secrets.token_hex(8)


class StaleLease(ConnectionError):
    """Master fenced us: the lease is revoked — re-hello, don't retry
    the same identity."""


class ProtocolDesync(ConnectionError):
    """Response doesn't match the request in flight (e.g. a network
    middlebox duplicated a frame): the req/resp pairing is lost, the
    only safe move is a fresh connection."""


class SlaveClient(Logger):
    def __init__(self, workflow, address, name=None, io_timeout=30.0,
                 retry_base=0.05, retry_max=2.0, max_retries=8,
                 ping_interval=1.0, grad_codec="none",
                 grad_topk_percent=1.0):
        from veles import compression
        self.name = name or "SlaveClient"
        self.workflow = workflow
        #: gradient wire codec OFFERED at hello (the master's config
        #: wins — see veles/server.py negotiation); validated here so
        #: a typo fails at construction, not at the first sync
        self.grad_codec = str(grad_codec or "none")
        if self.grad_codec not in compression.CODEC_NAMES:
            raise ValueError(
                "unknown grad codec %r (known: %s)"
                % (grad_codec, ", ".join(compression.CODEC_NAMES)))
        self.grad_topk_percent = float(grad_topk_percent)
        #: the codec actually negotiated (welcome's 4th element);
        #: tracked so a re-hello under the SAME codec keeps the
        #: error-feedback residuals instead of resetting them
        self._codec_active = None
        self.codec_fallbacks = 0
        #: True while talking to a pre-OOB master (detected per
        #: connection: a codec-aware hello always earns a 4-tuple
        #: welcome from a new master, so a 3-tuple back means OLD —
        #: pin our sends to legacy monolithic frames it can read)
        self._legacy_frames = False
        self._check_mode()
        host, _, port = str(address).rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        require_secret_for(self.address[0], "slave master")
        self.registry = DistributionRegistry(workflow)
        self.sock = None
        self.slave_id = None
        self.lease_id = None
        self.jobs_done = 0
        #: serializes whole-frame SENDS (and the pending-pong count):
        #: the heartbeat thread can ping while the main thread
        #: computes — or even between the main thread's send and
        #: recv — without ever interleaving bytes mid-frame. Reads
        #: are unserialized because the main thread is the ONLY
        #: reader (see the module docstring).
        self._io_lock = threading.Lock()
        self._hb_stop = None
        self._last_io = 0.0
        #: pings sent whose pongs the main reader has not yet drained
        #: (guarded by _io_lock; reset per connection)
        self._pending_pongs = 0
        #: per-request socket deadline — a silent master (or a dropped
        #: frame) unblocks here instead of hanging the slave forever
        self.io_timeout = float(io_timeout)
        #: reconnect policy: sleep retry_base·2^k (capped at
        #: retry_max, +0..25 % jitter so a restarted master isn't
        #: stampeded) for up to max_retries consecutive failures.
        #: ``None`` retries FOREVER — the right setting under a
        #: preemptible master (k8s reschedule takes minutes; a slave
        #: that gives up turns every master restart into lost capacity)
        self.retry_base = float(retry_base)
        self.retry_max = float(retry_max)
        self.max_retries = None if max_retries is None \
            else int(max_retries)
        #: heartbeat period while the master says ("wait",)
        self.ping_interval = float(ping_interval)
        #: preemption stop: request_stop() makes run_forever return
        #: after the in-flight job instead of requesting another
        self._stop = threading.Event()
        #: robustness counters (mirrors MasterServer.faults)
        self.reconnects = 0
        self.stale_resyncs = 0
        self.pings_sent = 0
        # telemetry: local mirrors of the attribute counters, plus the
        # last counter state acknowledged by the master (deltas against
        # it ride each update frame — see _telemetry_delta)
        self._tele = {
            key: telemetry.LazyChild(
                lambda name=name, help=help: telemetry.counter(
                    name, help))
            for key, name, help in (
                ("jobs", "veles_slave_jobs_done_total",
                 "Jobs completed and acknowledged by the master"),
                ("reconnects", "veles_slave_reconnects_total",
                 "Reconnect/re-hello cycles"),
                ("stale", "veles_slave_stale_resyncs_total",
                 "Lease revocations noticed (fenced responses)"),
                ("codec_fallback", "veles_slave_codec_fallbacks_total",
                 "Hellos where the master declined this slave's grad "
                 "codec and the sync fell back to 'none'"),
            )}
        #: stable token identifying this PROCESS's counter stream
        #: across re-hellos: the master diffs pushed absolute state
        #: per token, so a lost ok-ack (state absorbed, ack dropped,
        #: slave re-pushes under a fresh slave_id) or co-located
        #: clients pushing the same shared registry can never double-
        #: count — see MasterServer._absorb_telemetry
        self._push_token = _PUSH_TOKEN

    def connect(self):
        self.sock = socket.create_connection(self.address,
                                             timeout=self.io_timeout)
        self.sock.settimeout(self.io_timeout)
        send_frame(self.sock, ("hello", self.name, self.grad_codec))
        welcome = recv_frame(self.sock)
        # no asserts: they vanish under ``python -O`` and a bad
        # handshake must fail LOUDLY either way
        if welcome is None:
            raise ConnectionError(
                "master %s:%d closed the connection during handshake"
                % self.address)
        if not isinstance(welcome, tuple) or len(welcome) < 3 \
                or welcome[0] != "welcome":
            raise ConnectionError(
                "bad handshake from master %s:%d: expected "
                "('welcome', slave_id, lease_id), got %r"
                % (self.address + (welcome,)))
        self.slave_id, self.lease_id = welcome[1], welcome[2]
        self._legacy_frames = len(welcome) < 4
        self._adopt_codec(
            welcome[3] if len(welcome) > 3 else "none",
            welcome[4] if len(welcome) > 4 else None)
        # under the io lock: a previous connection's heartbeat thread
        # may still be mid-send and writes _last_io on exit — both
        # writers hold the lock, so the fresher timestamp wins
        # deterministically instead of racing
        with self._io_lock:
            self._last_io = time.monotonic()
            self._pending_pongs = 0
        self._start_heartbeat()
        return self

    def _adopt_codec(self, chosen, topk_percent=None):
        """Install the codec the master chose for this lease. A
        fallback (master config wins — old master, different config)
        is warned and counted, never fatal: the slave keeps training,
        uncompressed. The master's ``topk_percent`` rides the welcome
        and wins too — a locally-configured K would silently change
        how much of each delta ships. A re-hello under the SAME
        (codec, K) keeps the encoder instance, so the error-feedback
        residuals survive reconnects; a change discards them (they
        compensate a quantizer that no longer exists)."""
        from veles import compression
        if chosen != self.grad_codec:
            self.codec_fallbacks += 1
            self._tele["codec_fallback"].get().inc()
            self.warning(
                "master negotiated grad codec %r (this slave asked "
                "for %r) — syncing uncompressed", chosen,
                self.grad_codec)
        k = self.grad_topk_percent if topk_percent is None \
            else float(topk_percent)
        if k != self.grad_topk_percent:
            self.info("master imposed topk_percent %g (this slave "
                      "was configured with %g)", k,
                      self.grad_topk_percent)
        if (chosen, k) != self._codec_active:
            self.workflow.grad_codec = compression.get_codec(
                chosen, k)
            self._codec_active = (chosen, k)

    def _start_heartbeat(self):
        """Best-effort liveness pings whenever the socket has been
        idle for ``ping_interval`` — covers both ("wait",) parking and
        LONG LOCAL ITERATIONS, so the master's slave_timeout measures
        silence, not compute time. The thread is pinned to THIS
        connection's socket and is SEND-ONLY: it emits the whole ping
        frame under the io lock (never interleaving bytes mid-frame
        with an in-flight update send) and NEVER reads — the main
        thread is the sole reader and drains the owed pongs before
        its own responses (see ``_roundtrip``). Errors just stop the
        beat: the main loop's next round-trip surfaces them with full
        reconnect handling."""
        if self.ping_interval <= 0:
            return
        self._hb_stop = stop = threading.Event()
        sock = self.sock

        def beat():
            while not stop.wait(self.ping_interval):
                try:
                    if time.monotonic() - self._last_io \
                            < self.ping_interval:
                        continue
                    with self._io_lock:
                        if self.sock is not sock or stop.is_set():
                            return
                        send_frame(sock, ("ping", self.slave_id,
                                          self.lease_id))
                        self._pending_pongs += 1
                        self._last_io = time.monotonic()
                    self.pings_sent += 1
                except Exception:
                    return
        threading.Thread(target=beat, daemon=True,
                         name="%s-heartbeat" % self.name).start()

    def _check_mode(self):
        """A slave must serve the indices the MASTER assigns per job;
        fused whole-epoch dispatch owns its own minibatch order, so a
        workflow initialized without ``is_slave = True`` (the Launcher
        sets it before initialize) is rejected LOUDLY. Re-checked per
        job, since initialize() may happen after construction."""
        step = getattr(self.workflow, "xla_step", None)
        if step is not None and (step.scan_mode or step.stream_mode):
            raise ValueError(
                "slave workflow was initialized in fused dispatch "
                "mode; set workflow.is_slave = True before "
                "initialize()")

    def _roundtrip(self, request):
        sock = self.sock
        with self._io_lock:
            send_frame(sock, request, legacy=self._legacy_frames)
            self._last_io = time.monotonic()
        # reads are lock-free: this thread is the ONLY reader.
        # Responses arrive in request order, so any pongs owed to
        # heartbeat pings sent BEFORE our request drain first; a pong
        # we never paid for is a genuine desync.
        while True:
            resp = recv_frame(sock)
            with self._io_lock:
                self._last_io = time.monotonic()
                if resp is not None and isinstance(resp, tuple) \
                        and resp and resp[0] == "pong":
                    if self._pending_pongs > 0:
                        self._pending_pongs -= 1
                        continue
                    raise ProtocolDesync(
                        "unsolicited pong (no heartbeat ping "
                        "outstanding)")
            break
        if resp is None:
            raise ConnectionError("master closed the connection")
        if resp == ("stale",):
            self.stale_resyncs += 1
            self._tele["stale"].get().inc()
            telemetry.record_event(
                "lease_stale", request=str(request[0]),
                slave=self.slave_id)
            raise StaleLease(
                "master fenced %r for slave %s — lease %s revoked"
                % (request[0], self.slave_id, self.lease_id))
        return resp

    def run_one(self):
        """Request + run one job; False when the master says stop."""
        self._check_mode()
        resp = self._roundtrip(("job", self.slave_id, self.lease_id))
        if resp[0] == "bye":
            return False
        if resp[0] == "wait":
            time.sleep(0.02)
            return True
        if resp[0] != "job" or len(resp) < 4:
            raise ProtocolDesync(
                "expected a job, got %r" % (resp[:1],))
        _, payload, job_id, epoch = resp[:4]
        # the master-minted trace context (5th element; absent from a
        # pre-ISSUE-6 master): every phase span below joins that trace
        ctx = telemetry.TraceContext.from_wire(resp[4]) \
            if len(resp) > 4 else None
        spans = []
        # bind the job's trace for the whole local iteration: log
        # lines emitted while computing on its behalf carry the ids
        # (JSONL sink — veles/logger.py) and join /debug/trace spans
        with telemetry.context(ctx):
            t0 = time.perf_counter()
            self.registry.apply_job(payload)
            t1 = time.perf_counter()
            self._job_span(spans, ctx, "slave.apply", t0, t1 - t0,
                           job_id)
            self._run_iteration()
            t2 = time.perf_counter()
            self._job_span(spans, ctx, "slave.compute", t1, t2 - t1,
                           job_id)
        # count the job BEFORE building the pushed state: the state
        # rides the update that completes this very job, so the master
        # sees N jobs after N accepted updates (post-ack counting
        # would lag by one forever — the final job's increment has no
        # later update to ride). If THIS update is fenced/lost the
        # master doesn't absorb, and the next accepted push carries
        # the cumulative value — at-least-once on the fault path,
        # exact on the fault-free one.
        self._tele["jobs"].get().inc()
        update = self.registry.generate_update()
        t3 = time.perf_counter()
        self._job_span(spans, ctx, "slave.update_build", t2, t3 - t2,
                       job_id)
        tele = self._telemetry_state() or {"token": self._push_token}
        # total job wall time: what the master subtracts from its
        # serve→update round-trip to attribute the WIRE portion
        tele["job_seconds"] = t3 - t0
        # model-health summary (ISSUE 15): compact per-layer stats +
        # verdict ride the same __telemetry__ side channel, so the
        # master republishes them slave-labelled and ONE scrape sees
        # cluster-wide training health. Skipped while this process
        # has no observations yet (nothing to ship).
        from veles import model_health
        summary = model_health.get_model_monitor().push_summary()
        if summary["layers"] or summary["loss"] is not None:
            tele["model"] = summary
        if spans:
            tele["spans"] = spans
        update["__telemetry__"] = tele
        ok = self._roundtrip(
            ("update", self.slave_id, self.lease_id, job_id, epoch,
             update))
        if ok[0] != "ok":
            raise ProtocolDesync("expected ok, got %r" % (ok[:1],))
        self.jobs_done += 1
        return True

    def _job_span(self, spans, ctx, name, start, duration, job_id):
        """Append one completed job-phase span to the SHIPPED list
        (wall-clock anchored so the master can merge it into its own
        timeline). Not recorded into the local tracer: the master's
        absorb is the single recording point, so a co-located
        master+slave pair (shared tracer) never sees duplicates."""
        args = {"job_id": job_id, "slave": self.slave_id}
        if ctx is not None:
            args.update(ctx.child().span_args())
        spans.append({
            "name": name,
            "wall": time.time() - (time.perf_counter() - start),
            "dur": duration, "pid": os.getpid(),
            "tid": threading.get_ident(), "args": args})

    def _telemetry_state(self):
        """The ABSOLUTE counter state pushed on each update — what
        makes one scrape of the master show the whole cluster. Absolute
        values + the stable token make the push idempotent: the master
        increments by the per-token diff, so retransmits after a lost
        ack (or a re-hello) are no-ops rather than double counts."""
        state = telemetry.get_registry().counter_state(
            exclude_prefixes=_NO_PUSH_PREFIXES,
            exclude_label_keys=("slave",))
        if not state:
            return None
        return {"token": self._push_token, "state": state}

    def _run_iteration(self):
        """One forward/backward/update pass over the minibatch the
        master assigned (already applied into the loader)."""
        wf = self.workflow
        if wf.xla_step is not None:
            # master pushed fresh weights into host Arrays: re-upload,
            # step, and sync back so generate_update ships the result
            wf.xla_step.refresh_device()
            wf.xla_step.run()
            wf.xla_step.sync_host()
        else:
            for u in wf.forwards:
                u.run()
            wf.evaluator.run()
            if wf.loader.minibatch_class == CLASS_TRAIN:
                for gd in reversed(wf.gds):
                    gd.run()

    def _close_sock(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def _backoff(self, attempt):
        # clamp the exponent: with max_retries=None attempt grows
        # without bound, and 2**1030 no longer converts to float —
        # retry_max caps the delay long before 2**32 anyway
        delay = min(self.retry_max,
                    self.retry_base * (2.0 ** min(32, max(0, attempt - 1))))
        return delay * (1.0 + 0.25 * random.random())

    def run_forever(self):
        """Pump jobs until the master says ``bye``, surviving master
        restarts, revoked leases and connection hiccups: reconnect +
        re-hello with exponential backoff, giving up only after
        ``max_retries`` consecutive failures without progress.
        :meth:`request_stop` (the Launcher's SIGTERM relay) breaks the
        loop at the next job boundary — a preempted slave exits
        cleanly instead of pulling jobs for the whole grace period."""
        attempt = 0
        while not self._stop.is_set():
            try:
                if self.sock is None:
                    self.connect()
                if not self.run_one():
                    break
                attempt = 0           # progress resets the budget
            except (ConnectionError, OSError) as exc:
                # socket.timeout is an OSError; StaleLease and
                # ProtocolDesync are ConnectionErrors. A StaleLease is
                # the normal zombie outcome (the master already
                # requeued our in-flight work when it dropped us), the
                # rest are network trouble — either way the old
                # identity is abandoned cleanly (id/lease zeroed so no
                # further frame can reuse them) and we re-hello, with
                # the same consecutive-failure budget guarding against
                # a master that fences or drops us forever.
                attempt += 1
                if self.max_retries is not None \
                        and attempt > self.max_retries:
                    self._close_sock()
                    raise ConnectionError(
                        "giving up on master %s:%d after %d failed "
                        "attempts (last: %s)"
                        % (self.address + (attempt - 1, exc)))
                self.warning(
                    "%s: %s; re-sync %d/%s", type(exc).__name__, exc,
                    attempt, "inf" if self.max_retries is None
                    else self.max_retries)
                self._resync(attempt)
        self._close_sock()
        self.info("slave done after %d jobs (%d reconnects, %d stale "
                  "re-syncs)", self.jobs_done, self.reconnects,
                  self.stale_resyncs)
        return self.jobs_done

    def request_stop(self):
        """Preemption (Launcher SIGTERM): finish the in-flight job,
        then return from run_forever instead of requesting another —
        the master requeues anything unmerged when the connection
        drops. Signal-safe: one Event.set, no locks, no I/O."""
        self._stop.set()

    def _resync(self, attempt):
        self._close_sock()
        self.slave_id = self.lease_id = None
        self.reconnects += 1
        self._tele["reconnects"].get().inc()
        telemetry.record_event("reconnect", name=self.name,
                               attempt=attempt)
        # interruptible backoff: a preempted slave must exit now, not
        # after its reconnect sleep runs out
        self._stop.wait(self._backoff(attempt))
