"""Ensemble training and evaluation.

Re-design of ``veles/ensemble/`` [U] (SURVEY.md §2.7 "Ensemble", L9):
train N instances of a workflow under different seeds (and optionally
different config overrides), then aggregate their predictions at eval
time. The reference ran these as separate velescli invocations writing
result files; the rebuild trains in-process via the same
``workflow_factory`` the samples expose, which keeps the fused-XLA
path and lets callers parallelize instances however they like.

Aggregation is mean-of-outputs (softmax probabilities average into a
valid categorical; MSE outputs average into the ensemble regression),
the reference's scheme."""

import numpy

from veles import prng
from veles.logger import Logger


class Ensemble(Logger):
    """Trains and evaluates a bag of workflow instances."""

    def __init__(self, workflow_factory, n_models=3, base_seed=1000,
                 device="numpy", name="ensemble"):
        self.name = name
        self.workflow_factory = workflow_factory
        self.n_models = int(n_models)
        self.base_seed = int(base_seed)
        self.device = device
        self.workflows = []

    def train(self):
        """Train every member (each under its own seed universe)."""
        for i in range(self.n_models):
            prng.seed_all(self.base_seed + i)
            wf = self.workflow_factory("%s_m%d" % (self.name, i))
            wf.initialize(device=self.device)
            wf.run()
            best = getattr(wf.decision, "best_metric", None)
            self.info("member %d trained: best metric %s", i, best)
            self.workflows.append(wf)
        return self.workflows

    # -- aggregation ---------------------------------------------------

    def _member_outputs(self, x):
        """Forward ``x`` through every member (numpy path on the
        synced weights); -> list of output arrays. Runs in EVAL phase:
        the last serve of training leaves train_phase True, and
        dropout/stochastic-pooling must not randomize predictions."""
        outs = []
        for wf in self.workflows:
            step = getattr(wf, "xla_step", None)
            if step is not None:
                step.sync_host()
            loader = wf.loader
            was_train = bool(loader.train_phase)
            loader.train_phase << False
            try:
                loader.minibatch_data.map_invalidate()
                loader.minibatch_data.mem[...] = x
                for f in wf.forwards:
                    f.numpy_run()
                outs.append(numpy.array(
                    wf.forwards[-1].output.map_read().mem))
            finally:
                loader.train_phase << was_train
        return outs

    def predict(self, x):
        """Mean of member forward outputs on batch ``x``."""
        return numpy.mean(self._member_outputs(x), axis=0)

    def evaluate_classification(self):
        """Ensemble + per-member error rate over the validation class
        of member 0's loader (all members share the dataset contract)."""
        from veles.loader.base import CLASS_VALID
        loader = self.workflows[0].loader
        data = numpy.asarray(loader.original_data.map_read().mem,
                             numpy.float32)
        labels = numpy.asarray(loader.original_labels.map_read().mem)
        # validation samples live in the class-order layout
        # [test | valid | train]
        n_test = loader.class_lengths[0]
        n_valid = loader.class_lengths[CLASS_VALID]
        vx = data[n_test:n_test + n_valid]
        vy = labels[n_test:n_test + n_valid]
        mb = loader.max_minibatch_size
        member_preds = [[] for _ in self.workflows]
        ens_pred = []
        for lo in range(0, len(vx), mb):
            chunk = vx[lo:lo + mb]
            valid = len(chunk)
            if valid < mb:
                chunk = numpy.concatenate(
                    [chunk, numpy.repeat(chunk[-1:], mb - valid,
                                         axis=0)])
            outs = self._member_outputs(chunk)
            for i, out in enumerate(outs):
                member_preds[i].append(
                    numpy.argmax(out, axis=-1)[:valid])
            ens_pred.append(numpy.argmax(
                numpy.mean(outs, axis=0), axis=-1)[:valid])
        ens_pred = numpy.concatenate(ens_pred)
        ens_err = float(numpy.mean(ens_pred != vy))
        members = [float(numpy.mean(numpy.concatenate(p) != vy))
                   for p in member_preds]
        return {"ensemble_error": ens_err, "member_errors": members,
                "n_valid": int(len(vy))}
