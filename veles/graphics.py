"""Graphics pipeline — plot streaming to a separate renderer process.

Re-design of ``veles/graphics_server.py`` / ``graphics_client.py`` [U]
(SURVEY.md §2.7 "Graphics pipeline", §5.5): the reference pickled plot
units onto a ZMQ PUB socket and a separate matplotlib process rendered
them. The rebuild keeps the two-process shape (rendering must never
block the training loop) with a dependency-free transport:

* frames are **npz, not pickle** — a plot payload is numpy arrays + a
  JSON meta dict, so the renderer never deserializes executable
  content (unlike the master/slave channel, which needs arbitrary
  objects and pays for it with HMAC — ``veles/server.py``);
* localhost TCP, length-prefixed; the renderer subprocess is spawned
  by :class:`GraphicsServer` and exits when the socket closes.

``publish()`` is fire-and-forget from the training side: a dead or
slow renderer drops frames rather than stalling the run (plots are off
the hot path by design — SURVEY.md §5.8).
"""

import io
import json
import socket
import subprocess
import sys
import threading

import numpy

from veles.logger import Logger
from veles.server import recv_raw_frame, send_raw_frame


def pack_payload(meta, arrays):
    """(meta dict, {name: ndarray}) -> npz frame bytes."""
    buf = io.BytesIO()
    numpy.savez_compressed(
        buf, __meta__=numpy.frombuffer(
            json.dumps(meta).encode(), numpy.uint8), **arrays)
    return buf.getvalue()


def unpack_payload(blob):
    """npz frame bytes -> (meta dict, {name: ndarray})."""
    with numpy.load(io.BytesIO(blob), allow_pickle=False) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    return meta, arrays


def send_frame(sock, blob):
    """npz blob -> wire: the HARDENED raw framing from veles/server.py
    (this module used to keep a private uncapped clone — length cap
    and exact-recv now have exactly one implementation)."""
    send_raw_frame(sock, blob)


def recv_frame(sock):
    return recv_raw_frame(sock)


class GraphicsServer(Logger):
    """Accepts one renderer connection and streams plot frames to it.

    ``out_dir`` is where the spawned renderer writes PNGs. Pass
    ``spawn_client=False`` to attach an external renderer instead
    (reference: many viewers could subscribe; one renderer is enough
    for the file backend)."""

    def __init__(self, out_dir, spawn_client=True, name="graphics"):
        self.name = name
        self.out_dir = out_dir
        self._listener = socket.create_server(("127.0.0.1", 0))
        self.port = self._listener.getsockname()[1]
        self._conn = None
        self._lock = threading.Lock()
        self._dropped = 0
        self.client = None
        self._accept_thread = threading.Thread(
            target=self._accept, daemon=True,
            name="%s-accept" % self.name)
        self._accept_thread.start()
        if spawn_client:
            self.client = subprocess.Popen(
                [sys.executable, "-m", "veles.graphics_client",
                 "--connect", str(self.port), "--out", out_dir],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    #: max seconds publish() may spend inside the kernel send buffer;
    #: past this the renderer is declared too slow and LOSES THE FEED
    #: (a timed-out sendall leaves a half frame on the wire, so the
    #: connection cannot be kept)
    send_timeout = 5.0

    def _accept(self):
        try:
            conn, _ = self._listener.accept()
            conn.settimeout(self.send_timeout)
            with self._lock:
                self._conn = conn
        except OSError:
            pass  # listener closed before anyone connected

    def publish(self, meta, arrays):
        """Fire-and-forget: drop the frame if no renderer is attached,
        the pipe broke, or the renderer is too slow to keep up — the
        training loop must never stall on plotting."""
        if self._conn is None:      # don't even serialize for nobody
            self._dropped += 1
            return False
        blob = pack_payload(meta, arrays)
        with self._lock:
            conn = self._conn
            if conn is None:
                self._dropped += 1
                return False
            try:
                send_frame(conn, blob)
                return True
            except (OSError, socket.timeout):
                self._dropped += 1
                self._conn = None
                conn.close()
                self.warning(
                    "renderer lost (%d frame(s) dropped so far)",
                    self._dropped)
                return False

    def close(self, wait=True):
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                self._conn.close()
                self._conn = None
        self._listener.close()
        if self.client is not None and wait:
            try:
                self.client.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.client.kill()
