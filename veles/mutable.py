"""Mutable gate booleans and cross-unit attribute links.

Re-design of ``veles/mutable.py`` [U] (SURVEY.md §2.1 "Mutable bools /
links"). ``Bool`` is a shared, mutable truth value used as a unit gate
(``gate_block`` / ``gate_skip``); boolean algebra over Bools produces
*derived* bools that re-evaluate lazily, so ``decision.complete &
~loader.epoch_ended`` stays live as its operands flip. ``LinkableAttribute``
aliases an attribute of one object to an attribute of another (the data
edges created by ``Unit.link_attrs``).
"""

import operator

_MISSING = object()


class Bool:
    """A mutable boolean with lazy operator composition.

    ``b << True`` (or ``b.set(True)``) mutates in place; ``&``, ``|``,
    ``^`` and ``~`` build derived Bools that track their operands.
    """

    __slots__ = ("_value", "_op", "_operands", "on_change")

    def __init__(self, value=False):
        self._value = bool(value)
        self._op = None
        self._operands = ()
        self.on_change = None

    # -- mutation -----------------------------------------------------

    def set(self, value) -> "Bool":
        if self._op is not None:
            raise ValueError("cannot assign to a derived Bool")
        value = bool(value)
        changed = value != self._value
        self._value = value
        if changed and self.on_change is not None:
            self.on_change(self)
        return self

    def __lshift__(self, value) -> "Bool":
        return self.set(value)

    def toggle(self) -> "Bool":
        return self.set(not self._value)

    # -- evaluation ---------------------------------------------------

    def __bool__(self) -> bool:
        if self._op is None:
            return self._value
        return bool(self._op(*[bool(b) for b in self._operands]))

    @classmethod
    def _derived(cls, op, *operands) -> "Bool":
        b = cls()
        b._op = op
        b._operands = operands
        return b

    def __and__(self, other):
        return Bool._derived(operator.and_, self, _coerce(other))

    __rand__ = __and__

    def __or__(self, other):
        return Bool._derived(operator.or_, self, _coerce(other))

    __ror__ = __or__

    def __xor__(self, other):
        return Bool._derived(operator.xor, self, _coerce(other))

    __rxor__ = __xor__

    def __invert__(self):
        return Bool._derived(operator.not_, self)

    def __repr__(self):
        kind = "derived " if self._op is not None else ""
        return "<%sBool %s>" % (kind, bool(self))


def _coerce(value) -> Bool:
    return value if isinstance(value, Bool) else Bool(value)


class LinkableAttribute:
    """Alias ``getattr(dst, dst_attr)`` to ``getattr(src, src_attr)``.

    Installed as a class-level descriptor slot on the destination's type
    with a per-instance mapping, so different instances of one unit class
    can link to different sources (matching the reference's per-instance
    ``link_attrs`` behaviour [U]).
    """

    def __init__(self, attr_name, class_default=_MISSING):
        self._attr = attr_name
        self._key = "_linked_" + attr_name
        self._class_default = class_default

    @staticmethod
    def install(dst, dst_attr, src, src_attr, two_way=False):
        cls = type(dst)
        descr = cls.__dict__.get(dst_attr)
        if not isinstance(descr, LinkableAttribute):
            # Capture any shadowed class-level default (from this class
            # or the MRO) so unlinked instances keep seeing it.
            default = _MISSING
            for base in cls.__mro__:
                if dst_attr in base.__dict__:
                    default = base.__dict__[dst_attr]
                    break
            # Preserve any plain value already on the instance: keep it
            # in __dict__, where __get__ falls back to it.
            descr = LinkableAttribute(dst_attr, default)
            setattr(cls, dst_attr, descr)
        dst.__dict__[descr._key] = (src, src_attr, two_way)
        return descr

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        link = instance.__dict__.get(self._key)
        if link is None:
            if self._attr in instance.__dict__:
                return instance.__dict__[self._attr]
            if self._class_default is not _MISSING:
                return self._class_default
            raise AttributeError(self._attr)
        src, src_attr, _ = link
        return getattr(src, src_attr)

    def __set__(self, instance, value):
        link = instance.__dict__.get(self._key)
        if link is None:
            instance.__dict__[self._attr] = value
            return
        src, src_attr, two_way = link
        if two_way:
            setattr(src, src_attr, value)
        else:
            # Writing to a one-way linked attribute breaks the link,
            # mirroring the reference's unlink-on-assign behaviour.
            instance.__dict__.pop(self._key, None)
            instance.__dict__[self._attr] = value
