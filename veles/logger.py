"""Logger mixin giving every unit ``info/debug/warning/error`` methods.

Re-design of ``veles/logger.py`` [U] (SURVEY.md §2.1 "Logger"): colored
console output keyed by logger name; the optional MongoDB shipping of the
reference is replaced by an optional JSONL sink (no external services in
the TPU build).
"""

import json
import logging
import os
import sys
import traceback

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def __init__(self, colored: bool):
        super().__init__(
            fmt="%(asctime)s %(levelname)-7s %(name)s: %(message)s",
            datefmt="%H:%M:%S")
        self._colored = colored

    def format(self, record):
        text = super().format(record)
        if self._colored:
            color = _COLORS.get(record.levelno, "")
            return "%s%s%s" % (color, text, _RESET) if color else text
        return text


class _JsonlHandler(logging.Handler):
    """Optional structured sink (stands in for the reference's MongoDB
    log shipping, which needs a server we don't assume)."""

    def __init__(self, path: str):
        super().__init__()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fp = open(path, "a", buffering=1)

    def emit(self, record):
        try:
            doc = {
                # the record's own timestamp, not a second
                # time.time() call (keeps JSONL rows ordered exactly
                # like the console lines they mirror)
                "t": record.created,
                "level": record.levelname,
                "name": record.name,
                "msg": record.getMessage(),
            }
            # trace correlation: when the emitting thread works on
            # behalf of a traced request/job (telemetry.context), the
            # line carries the ids so /debug/trace spans and JSONL
            # rows join on one key. Imported lazily — logging must
            # never depend on telemetry import order.
            try:
                from veles import telemetry
                ctx = telemetry.current_context()
            except Exception:
                ctx = None
            if ctx is not None:
                doc["trace_id"] = ctx.trace_id
                doc["span_id"] = ctx.span_id
            if record.exc_info:
                # serialize the formatted traceback: structured logs
                # must be usable for postmortems, and exc_info itself
                # is not JSON-serializable
                doc["exc"] = "".join(traceback.format_exception(
                    *record.exc_info)).rstrip("\n")
            elif record.exc_text:
                doc["exc"] = record.exc_text
            self._fp.write(json.dumps(doc) + "\n")
        except Exception:  # pragma: no cover - never break on logging
            self.handleError(record)


_configured = False
_jsonl_paths = set()


def setup_logging(level=logging.INFO, jsonl_path=None):
    global _configured
    root_logger = logging.getLogger()
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_ColorFormatter(sys.stderr.isatty()))
        root_logger.addHandler(handler)
        _configured = True
    root_logger.setLevel(level)
    if jsonl_path and jsonl_path not in _jsonl_paths:
        _jsonl_paths.add(jsonl_path)
        root_logger.addHandler(_JsonlHandler(jsonl_path))


class Logger:
    """Mixin: self.info/debug/warning/error, named after the class (and
    the unit name when mixed into :class:`veles.units.Unit`)."""

    @property
    def logger(self) -> logging.Logger:
        cached = self.__dict__.get("_logger")
        name = getattr(self, "name", None) or type(self).__name__
        if cached is None or cached.name != name:
            cached = logging.getLogger(name)
            self.__dict__["_logger"] = cached
        return cached

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def exception(self, msg, *args):
        self.logger.exception(msg, *args)
