"""Genetic hyperparameter search over ``Tune`` config leaves.

Re-design of ``veles/genetics/`` [U] (SURVEY.md §2.7 "Genetics", L9):
config values wrapped in ``Tune(default, min, max)`` define the search
space; each individual is one full (short) training run; fitness is
the run's validation metric (lower is better). Like the reference,
individuals distribute over SLAVES (``GATaskServer`` +
``ga_slave_loop`` over the HMAC-framed TCP protocol, with the same
drop->requeue elasticity as the training master; CLI:
``--optimize ... --listen-address`` / ``--optimize slave
--master-address``); ``ProcessPoolMap`` is the local spawned-worker
fallback, and any caller-supplied ``map_fn`` plugs in (the TPU
analogue: one individual per device/slice).

The optimizer is deliberately classic (tournament selection, blend
crossover, gaussian mutation, elitism) and fully seeded: same seed ⇒
same search trajectory, matching the framework's determinism contract
(SURVEY.md §4).
"""

import numpy

from veles.config import Config, Tune
from veles.logger import Logger


def find_tunables(node, prefix=""):
    """Deep search for Tune leaves through Config nodes AND plain
    dict/list values (layer specs are dicts inside a list leaf — the
    reference's Tunes lived there too [U]). Paths are '/'-separated
    segments; list positions are numeric segments."""
    if isinstance(node, Config):
        it = node.items()
    elif isinstance(node, dict):
        it = node.items()
    elif isinstance(node, (list, tuple)):
        it = enumerate(node)
    else:
        return {}
    out = {}
    for key, value in it:
        path = "%s/%s" % (prefix, key) if prefix else str(key)
        if isinstance(value, Tune):
            out[path] = value
        else:
            out.update(find_tunables(value, path))
    return out


class _SafeEval:
    """Picklable failure-absorbing wrapper around the fitness
    callable: a crashed individual scores inf instead of killing the
    search (reference behaviour — a diverged run is just unfit).

    Returns ``(fitness, error_or_None)`` — the error string rides back
    through the (possibly cross-process) map so ``_fitness_of`` can
    say WHY individuals failed; a bare inf from a worker would lose
    the traceback entirely."""

    def __init__(self, evaluate):
        self.evaluate = evaluate

    def __call__(self, values):
        try:
            return float(self.evaluate(values)), None
        except Exception as exc:
            return float("inf"), "%s: %s" % (type(exc).__name__, exc)


class ProcessPoolMap:
    """``map_fn`` evaluating a whole population concurrently in worker
    processes — the rebuild's answer to the reference distributing GA
    individuals over slaves (SURVEY.md §2.7 "Genetics"): one short
    training run per individual, N at a time. Uses the ``spawn``
    context so each worker gets a fresh interpreter (fresh jax/XLA
    state, no fork-after-threads hazards). The callable shipped to
    workers must be picklable (``SubprocessTrainer`` is).

    Determinism: results are returned in population order and every
    individual carries its own seed, so a parallel generation scores
    exactly like a sequential one."""

    def __init__(self, n_workers=None):
        import os
        self.n_workers = int(n_workers or min(os.cpu_count() or 1, 8))
        self._pool = None

    def _ensure(self):
        if self._pool is None:
            import multiprocessing
            ctx = multiprocessing.get_context("spawn")
            self._pool = ctx.Pool(self.n_workers)
        return self._pool

    def __call__(self, f, xs):
        xs = list(xs)
        if not xs:
            return []
        if len(xs) == 1:   # no point paying a worker round-trip
            return [f(xs[0])]
        return self._ensure().map(f, xs)

    def close(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SubprocessTrainer:
    """Picklable GA fitness: train ``workflow_path`` with the given
    config/overrides plus the individual's values, return the best
    validation metric. Runs inside ProcessPoolMap workers (each one a
    fresh spawned interpreter), standalone from the CLI Main object."""

    def __init__(self, workflow_path, config_path=None, overrides=(),
                 seed=1, device="numpy", max_epochs=None):
        self.workflow_path = workflow_path
        self.config_path = config_path
        self.overrides = tuple(overrides)
        self.seed = int(seed)
        self.device = device
        self.max_epochs = max_epochs

    def __call__(self, values):
        import veles.prng as prng
        from veles.config import root
        from veles.__main__ import import_file
        # workflow module FIRST: its module-level defaults land in
        # root before the config file / overrides (Main.run ordering)
        module = import_file(self.workflow_path)
        if self.config_path:
            import_file(self.config_path, "veles_config_module")
        for override in self.overrides:
            root.apply_override(override)
        apply_values(root, values)
        prng.seed_all(self.seed)   # identical universe per individual
        holder = {}

        def load(WorkflowClass, **kwargs):
            holder["wf"] = WorkflowClass(None, **kwargs)
            return holder["wf"]

        def main(**kwargs):
            wf = holder["wf"]
            if (self.max_epochs is not None
                    and getattr(wf, "decision", None) is not None):
                wf.decision.max_epochs = int(self.max_epochs)
            wf.initialize(device=self.device)
            wf.run()

        module.run(load, main)
        return float(holder["wf"].decision.best_metric)


class GeneticOptimizer(Logger):
    """Minimizes ``evaluate(values)`` over the box defined by
    ``tunables`` (a ``{path: Tune}`` dict from ``Config.tunables()``).

    ``evaluate`` receives ``{path: value}`` and returns a scalar
    fitness (lower = better; NaN/inf = failed individual)."""

    def __init__(self, evaluate, tunables, population_size=12,
                 generations=8, elite=2, tournament=3,
                 mutation_rate=0.25, mutation_sigma=0.2, seed=1,
                 map_fn=None, name="genetics"):
        if not tunables:
            raise ValueError("nothing to optimize: no Tune leaves")
        self.name = name
        self.evaluate = evaluate
        self.paths = sorted(tunables)
        self.tunables = tunables
        self.population_size = int(population_size)
        self.generations = int(generations)
        self.elite = int(elite)
        self.tournament = int(tournament)
        self.mutation_rate = float(mutation_rate)
        self.mutation_sigma = float(mutation_sigma)
        self.map_fn = map_fn or (lambda f, xs: [f(x) for x in xs])
        self._gen = numpy.random.Generator(numpy.random.PCG64(seed))
        #: (fitness, values) per generation champion
        self.history = []
        self.best_values = None
        self.best_fitness = numpy.inf
        self.evaluations = 0

    # -- genome <-> values --------------------------------------------

    def _decode(self, genome):
        out = {}
        for x, path in zip(genome, self.paths):
            out[path] = self.tunables[path].clip(x)
        return out

    def _spans(self):
        lo = numpy.array([self.tunables[p].min_value
                          for p in self.paths], float)
        hi = numpy.array([self.tunables[p].max_value
                          for p in self.paths], float)
        return lo, hi

    # -- operators -----------------------------------------------------

    def _initial_population(self):
        lo, hi = self._spans()
        pop = self._gen.uniform(lo, hi,
                                (self.population_size, len(lo)))
        # seed the defaults as individual 0 — the search must never be
        # worse than the hand-tuned config
        pop[0] = [float(self.tunables[p].default) for p in self.paths]
        return pop

    def _select(self, fitness):
        idx = self._gen.integers(0, len(fitness), self.tournament)
        return idx[numpy.argmin(fitness[idx])]

    def _crossover(self, a, b):
        # BLX-style blend: child uniform in the (slightly widened)
        # interval spanned by the parents
        lo = numpy.minimum(a, b)
        hi = numpy.maximum(a, b)
        span = hi - lo
        return self._gen.uniform(lo - 0.1 * span, hi + 0.1 * span)

    def _mutate(self, genome):
        lo, hi = self._spans()
        mask = self._gen.random(len(genome)) < self.mutation_rate
        noise = self._gen.normal(0.0, self.mutation_sigma,
                                 len(genome)) * (hi - lo)
        return numpy.where(mask, genome + noise, genome)

    # -- the search ----------------------------------------------------

    def _fitness_of(self, pop):
        vals = [self._decode(g) for g in pop]
        # _SafeEval is a module-level picklable wrapper so a parallel
        # map_fn (ProcessPoolMap) can ship it to worker processes —
        # the evaluate callable itself must then be picklable too
        # (e.g. SubprocessTrainer)
        # list() first: a lazy caller-supplied map_fn (builtin map)
        # must not be exhausted by the fitness pass before the error
        # pass reads it
        pairs = list(self.map_fn(_SafeEval(self.evaluate), vals))
        out = numpy.asarray([fit for fit, _ in pairs], float)
        errors = [msg for _, msg in pairs if msg]
        self.evaluations += len(vals)
        bad = int((~numpy.isfinite(out)).sum())
        if bad:
            self.warning("%d individual(s) failed this round (first: %s)",
                         bad, errors[0] if errors else "non-finite fitness")
        return numpy.where(numpy.isfinite(out), out, numpy.inf)

    def run(self):
        pop = self._initial_population()
        fitness = self._fitness_of(pop)
        for gen in range(self.generations):
            order = numpy.argsort(fitness)
            pop, fitness = pop[order], fitness[order]
            if fitness[0] < self.best_fitness:
                self.best_fitness = float(fitness[0])
                self.best_values = self._decode(pop[0])
            self.history.append(
                (float(fitness[0]), self._decode(pop[0])))
            self.info("generation %d: best %.6g %r", gen,
                      fitness[0], self.history[-1][1])
            children = list(pop[:self.elite])
            while len(children) < self.population_size:
                a = pop[self._select(fitness)]
                b = pop[self._select(fitness)]
                children.append(self._mutate(self._crossover(a, b)))
            pop = numpy.asarray(children)
            # elites keep their known fitness; only newcomers pay a run
            new_fit = self._fitness_of(pop[self.elite:])
            fitness = numpy.concatenate([fitness[:self.elite], new_fit])
        order = numpy.argsort(fitness)
        if fitness[order[0]] < self.best_fitness:
            self.best_fitness = float(fitness[order[0]])
            self.best_values = self._decode(pop[order[0]])
        return self.best_values, self.best_fitness


def apply_values(config_root, values):
    """Write ``{path: value}`` into the tree; paths use the
    '/'-segment syntax of :func:`find_tunables`."""
    for path, value in values.items():
        node = config_root
        segs = path.split("/")
        for seg in segs[:-1]:
            if isinstance(node, Config):
                node = node.raw(seg)
            elif isinstance(node, (list, tuple)):
                node = node[int(seg)]
            else:
                node = node[seg]
        last = segs[-1]
        if isinstance(node, Config):
            setattr(node, last, value)
        elif isinstance(node, list):
            node[int(last)] = value
        else:
            node[last] = value


def optimize_config(config_root, run_one, **kwargs):
    """Convenience driver for ``--optimize``: search every Tune under
    ``config_root``; ``run_one()`` trains with the CURRENT config and
    returns the validation metric. Returns the optimizer (best values
    applied to the config on exit)."""
    tunables = find_tunables(config_root)

    def evaluate(values):
        apply_values(config_root, values)
        return run_one()

    opt = GeneticOptimizer(evaluate, tunables, **kwargs)
    best_values, best_fitness = opt.run()
    if best_values is not None:
        apply_values(config_root, best_values)
    return opt


# -- distributed evaluation over slaves --------------------------------
#
# The reference's genetics "runs distributed over slaves" (SURVEY.md
# §2.7): each individual is a short training run farmed out to the
# cluster. The rebuild ships GA tasks over the SAME HMAC-framed TCP
# protocol the training master uses (veles/server.py frames), with
# the same elastic contract: a slave joining mid-generation starts
# pulling tasks, a slave dying mid-task gets its task requeued.


class GATaskServer(Logger):
    """Master side: a per-generation queue of (idx, fn, values) tasks
    served to registered slaves; results collected by index. ``fn``
    rides inside the (HMAC-authenticated) frame, so slaves are
    generic — they need no pre-shared evaluate callable."""

    def __init__(self, address="127.0.0.1:0", slave_timeout=3600.0):
        import threading
        from veles.server import framed_server, require_secret_for
        self.name = "GATaskServer"
        host, _, port = str(address).rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        require_secret_for(self.address[0], "GA master listen")
        self.lock = threading.RLock()
        self.done_event = threading.Event()
        self.results_ready = threading.Condition(self.lock)
        self.slaves = {}
        self._next_slave = 1
        self.queue = []              # pending task pool (idx order)
        self.tasks = {}              # idx -> (fn, values)
        self.inflight = {}           # slave_id -> idx
        self.results = {}            # idx -> result
        #: generation guard: task frames carry the epoch of the map()
        #: call that queued them and result frames echo it, so a
        #: timeout-dropped slave re-reporting AFTER the generation
        #: completed (the reconnect path) cannot poison a later
        #: generation's fitness under the same index
        self.map_epoch = 0
        # slave_timeout bounds a SILENT death (host power loss — no
        # FIN ever arrives): past it the handler drops the slave and
        # its task requeues. It must exceed the longest single
        # evaluation — a slave is legitimately mute while training.
        self._server = framed_server(
            self.address, self._handle, self.done_event,
            self.drop_slave, timeout=float(slave_timeout))
        # accepting starts inside framed_server() on the shared
        # reactor — no accept thread to spawn since ISSUE 9
        self.bound_address = self._server.server_address

    def _handle(self, request):
        kind = request[0]
        with self.lock:
            if kind == "hello":
                slave_id = self._next_slave
                self._next_slave += 1
                self.slaves[slave_id] = {"name": request[1],
                                         "tasks": 0}
                self.info("GA slave %d (%s) joined", slave_id,
                          request[1])
                return ("welcome", slave_id)
            if kind == "task":
                if self.done_event.is_set():
                    return ("bye",)
                if not self.queue:
                    return ("wait",)
                idx = self.queue.pop(0)
                self.inflight[request[1]] = idx
                fn, values = self.tasks[idx]
                return ("task", idx, fn, values, self.map_epoch)
            if kind == "result":
                try:
                    _, slave_id, idx, result, epoch = request
                except ValueError:
                    # arity skew (a slave from another build): refuse
                    # the frame cleanly instead of killing the handler
                    return ("error",
                            "malformed result frame (want 5 fields, "
                            "got %d) — mixed master/slave versions?"
                            % len(request))
                if epoch != self.map_epoch:
                    # stale re-report from a generation that already
                    # completed while the slave was dropped: discard
                    # (and release any stale in-flight claim so a
                    # later drop cannot requeue an old index)
                    self.warning(
                        "discarding result for task %d from map "
                        "epoch %d (current %d)", idx, epoch,
                        self.map_epoch)
                    if self.inflight.get(slave_id) == idx:
                        del self.inflight[slave_id]
                    return ("ok",)
                if self.inflight.get(slave_id) == idx:
                    del self.inflight[slave_id]
                self.results[idx] = result
                if slave_id in self.slaves:
                    self.slaves[slave_id]["tasks"] += 1
                self.results_ready.notify_all()
                return ("ok",)
        return ("error", "unknown request %r" % (kind,))

    def drop_slave(self, slave_id, clean=False):
        """Death mid-task -> the task goes back to the pending pool
        (same requeue contract as the training master; ``clean`` is
        the framed_server polite-bye flag — inflight is empty then,
        so the requeue below is a no-op)."""
        with self.lock:
            idx = self.inflight.pop(slave_id, None)
            if idx is not None and idx not in self.results:
                self.warning("GA slave %s died; requeueing task %d",
                             slave_id, idx)
                self.queue.insert(0, idx)
            self.slaves.pop(slave_id, None)

    def map(self, fn, values_list):
        """Distribute one generation; blocks until every result is in
        (tasks of dropped slaves are requeued for the survivors).
        Results come back in population order."""
        with self.lock:
            self.map_epoch += 1
            self.tasks = {i: (fn, v) for i, v in enumerate(values_list)}
            self.results = {}
            self.queue = list(range(len(values_list)))
            # stale in-flight entries are PREVIOUS-generation indices;
            # a later drop_slave must not requeue them into this one
            self.inflight.clear()
        with self.results_ready:
            while len(self.results) < len(self.tasks):
                self.results_ready.wait(timeout=0.5)
        return [self.results[i] for i in range(len(self.tasks))]

    # GeneticOptimizer map_fn surface
    def __call__(self, fn, xs):
        xs = list(xs)
        return self.map(fn, xs) if xs else []

    def status(self):
        with self.lock:
            return {"mode": "ga-master",
                    "n_slaves": len(self.slaves),
                    "pending": len(self.queue),
                    "inflight": dict(self.inflight)}

    def close(self):
        self.done_event.set()
        self._server.shutdown()
        self._server.server_close()   # release the listening socket

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def ga_slave_loop(address, name="ga-slave", max_tasks=None,
                  poll=0.02, eval_lock=None, reconnect_attempts=3,
                  reconnect_delay=1.0):
    """Slave side: join the GA master at ``address``, pull tasks,
    evaluate, report — until the master says bye (or ``max_tasks``
    served, for tests). ``eval_lock`` serializes evaluation when
    several in-process slaves share mutable globals (root config).

    A MID-RUN connection loss is not treated as "master finished":
    the master drops (and requeues the task of) any slave whose
    evaluation outlives its ``slave_timeout``, and before round 5 the
    dropped-but-healthy slave would mistake the closed socket for a
    clean shutdown and exit permanently — with every evaluation
    longer than the timeout, the whole pool would drain one task at a
    time into a silent livelock (ADVICE r4). Now the slave re-dials
    and re-registers (fresh slave id) up to ``reconnect_attempts``
    times; only when the master no longer answers does it exit. A
    finished evaluation is re-reported over the new connection, so
    the work survives the drop even when the master already requeued
    it (the result handler accepts results for any known index)."""
    import contextlib
    import socket
    import time as _time
    from veles.server import (
        require_secret_for, send_frame, recv_frame)
    host, _, port = str(address).rpartition(":")
    addr = (host or "127.0.0.1", int(port))
    require_secret_for(addr[0], "GA slave master")
    state = {"sock": None, "slave_id": None}

    def connect(first=False):
        sock = socket.create_connection(addr, timeout=30)
        try:
            send_frame(sock, ("hello", name))
            welcome = recv_frame(sock)
        except (ConnectionError, OSError):
            # a handshake that dies mid-frame must not leak the fd
            # into the retry loop's next attempt
            sock.close()
            raise
        if welcome is None or welcome[0] != "welcome":
            sock.close()
            if first:
                raise RuntimeError(
                    "GA master at %s:%d closed the connection during "
                    "the handshake (search already finished?)" % addr)
            return False
        state["sock"], state["slave_id"] = sock, welcome[1]
        return True

    def drop_sock():
        if state["sock"] is not None:
            state["sock"].close()
            state["sock"] = None

    def rpc(build_msg):
        """send+recv with one reconnect round: ``build_msg(slave_id)``
        so a re-registered identity is used on the retry. None =>
        the master is genuinely gone."""
        for _attempt in range(2):
            if state["sock"] is None:
                ok = False
                for _ in range(max(1, int(reconnect_attempts))):
                    try:
                        ok = connect()
                    except (ConnectionError, OSError):
                        ok = False
                    if ok:
                        break
                    _time.sleep(reconnect_delay)
                if not ok:
                    return None
            try:
                send_frame(state["sock"], build_msg(state["slave_id"]))
                resp = recv_frame(state["sock"])
            except (ConnectionError, OSError):
                resp = None
            if resp is not None:
                return resp
            drop_sock()
        return None

    connect(first=True)
    served = 0
    try:
        while max_tasks is None or served < max_tasks:
            resp = rpc(lambda sid: ("task", sid))
            if resp is None or resp[0] == "bye":
                break
            if resp[0] == "wait":
                _time.sleep(poll)
                continue
            if resp[0] != "task" or len(resp) != 5:
                # unknown frame (the server's ('error', msg) reply) or
                # arity skew (a master from another build): exit
                # cleanly instead of dying on unpack
                break
            _, idx, fn, values, epoch = resp
            with (eval_lock or contextlib.nullcontext()):
                result = fn(values)
            ack = rpc(lambda sid: ("result", sid, idx, result,
                                   epoch))
            if ack is None:
                break
            if ack[0] != "ok":
                # the server's ('error', msg) refusal (mixed
                # master/slave builds): the result was NOT accepted —
                # surface the server's message and stop instead of
                # counting the task as served (ADVICE r5)
                import logging
                logging.getLogger(name).error(
                    "GA master refused result for task %s: %s", idx,
                    ack[1] if len(ack) > 1 else ack)
                break
            served += 1
    finally:
        drop_sock()
    return served
