"""Per-step performance accounting (ISSUE 6 tentpole, piece 3).

The bench harness computes FLOPs and MFU offline; the RUNTIME never
knew how much arithmetic a compiled step performs, so throughput
regressions (the ``grad_sync_bytes_per_step`` plateau, the S=8192 MFU
gap — ROADMAP items 3/4) were bench-only numbers invisible to a
scrape. This module closes that gap:

* :func:`program_cost` derives FLOPs and bytes for a jitted step
  program **from its jaxpr** at compile time — ``lax.scan`` trip
  counts are multiplied through (XLA's own HLO cost analysis counts a
  ``while`` body ONCE, which under-reports an epoch-scan program by
  the scan length), ``pjit``/``remat``/``custom_*`` regions are
  walked recursively, ``dot_general``/``conv_general_dilated`` get
  exact multiply-add counts and everything else is estimated at one
  flop per output element;
* :class:`PerfLedger` caches one :class:`StepCost` per compiled
  program and publishes the ``veles_step_*`` metric families on every
  dispatch (see ``XLAStep``): ``veles_step_flops_total{kind}``,
  ``veles_step_bytes_total{kind}``, ``veles_step_mfu_ratio{kind}``
  (when the device peak is known — :func:`device_peak_flops`),
  ``veles_step_flops_per_second{kind}`` and samples/tokens-per-second
  gauges. One Prometheus scrape now carries honest compute
  accounting next to the wire counters
  (``veles_wire_bytes_total{direction}``, ``veles/server.py``).

Cost model caveats: FLOPs are lower-bound arithmetic counts (no
fusion modelling); ``bytes`` sums every equation's output footprint
(scan-multiplied) — a proxy for memory traffic, not an HBM simulator.
Both are deterministic functions of the jaxpr, which is what makes
them comparable across runs and hosts.
"""

import os
import threading
import time
import weakref

import numpy

from veles import telemetry


class StepCost:
    """Cost of ONE call of a compiled program. ``precision`` is the
    program's dominant matmul input class ("bf16" | "int8" | "fp8" —
    by dot-FLOPs share), so the MFU gauge scores a low-precision
    program against the peak those matmuls actually have."""

    __slots__ = ("flops", "bytes", "io_bytes", "precision")

    def __init__(self, flops=0.0, bytes=0.0, io_bytes=0.0,
                 precision="bf16"):
        self.flops = float(flops)
        self.bytes = float(bytes)
        self.io_bytes = float(io_bytes)
        self.precision = precision

    def __repr__(self):
        return ("StepCost(flops=%.4g, bytes=%.4g, io_bytes=%.4g, "
                "precision=%s)" % (self.flops, self.bytes,
                                   self.io_bytes, self.precision))


def _size(shape):
    return int(numpy.prod(shape, dtype=numpy.int64)) if shape else 1


def _aval_bytes(aval):
    try:
        return _size(aval.shape) * numpy.dtype(aval.dtype).itemsize
    except (TypeError, AttributeError):
        return 0


def _dot_flops(eqn):
    """2 · |out| · K for ``dot_general`` (multiply-add = 2 flops)."""
    out = eqn.outvars[0].aval
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    k = 1
    for d in lhs_contract:
        k *= lhs.shape[d]
    return 2.0 * _size(out.shape) * k


def _conv_flops(eqn):
    """2 · |out| · (kernel footprint per output feature)."""
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params["dimension_numbers"]
    out_feature_dim = dn.rhs_spec[0]
    per_out = 1
    for i, d in enumerate(rhs.shape):
        if i != out_feature_dim:
            per_out *= d
    return 2.0 * _size(out.shape) * per_out


def _inner_jaxprs(eqn):
    """(multiplier, jaxpr) pairs for an equation's nested programs."""
    params = eqn.params
    name = eqn.primitive.name
    if name == "scan":
        return [(int(params.get("length", 1)), params["jaxpr"])]
    if name == "while":
        # trip count is data-dependent: count the body ONCE (explicit
        # under-estimate; the training paths use scan, not while)
        return [(1, params["body_jaxpr"])]
    if name == "cond":
        # either branch may run: charge the most expensive one
        branches = params.get("branches", ())
        if not branches:
            return []
        costed = [(1, b) for b in branches]
        return [max(costed, key=lambda mb: _jaxpr_cost(
            getattr(mb[1], "jaxpr", mb[1]))[0])]
    out = []
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in params:
            out.append((1, params[key]))
    if "branches" in params and not out:
        out.extend((1, b) for b in params["branches"])
    return out


def _dot_precision(eqn):
    """Precision class of one dot_general by BOTH input dtypes: a
    dot only runs at an 8-bit rate when both operands share the
    class — a mixed int8×bf16 dot (e.g. a fused dequant consumer)
    upcasts and runs the wide rate, and scoring it against the
    doubled 8-bit peak would under-report MFU ~2x."""
    def cls(var):
        try:
            name = numpy.dtype(var.aval.dtype).name
        except (TypeError, AttributeError):
            return "bf16"
        if name in ("int8", "uint8"):
            return "int8"
        if name.startswith("float8"):
            return "fp8"
        return "bf16"
    lhs, rhs = cls(eqn.invars[0]), cls(eqn.invars[1])
    return lhs if lhs == rhs else "bf16"


def _jaxpr_cost(jaxpr, dot_prec=None):
    """(flops, bytes) of one jaxpr execution, recursing into nested
    programs with their trip-count multipliers. ``dot_prec`` (when a
    dict is passed) accumulates dot-FLOPs per precision class — the
    input to the program-precision call."""
    flops = 0.0
    nbytes = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        inner = _inner_jaxprs(eqn)
        if inner:
            for mult, sub in inner:
                sub_prec = {} if dot_prec is not None else None
                f, b = _jaxpr_cost(getattr(sub, "jaxpr", sub),
                                   sub_prec)
                flops += mult * f
                nbytes += mult * b
                if dot_prec is not None:
                    for k, v in sub_prec.items():
                        dot_prec[k] = dot_prec.get(k, 0.0) + mult * v
            continue
        if name == "dot_general":
            f = _dot_flops(eqn)
            flops += f
            if dot_prec is not None:
                k = _dot_precision(eqn)
                dot_prec[k] = dot_prec.get(k, 0.0) + f
        elif name == "conv_general_dilated":
            flops += _conv_flops(eqn)
        else:
            # elementwise/reduce estimate: one flop per output element
            flops += sum(_size(v.aval.shape) for v in eqn.outvars
                         if hasattr(v.aval, "shape"))
        nbytes += sum(_aval_bytes(v.aval) for v in eqn.outvars)
    return flops, nbytes


def program_cost(fn, args):
    """Trace ``fn(*args)`` to a jaxpr (no XLA compilation, no
    execution, nothing donated) and walk it; -> :class:`StepCost`.
    The dominant dot-input precision class rides along so MFU is
    scored against the right peak for int8/fp8 programs."""
    import jax
    closed = jax.make_jaxpr(fn)(*args)
    dot_prec = {}
    flops, nbytes = _jaxpr_cost(closed.jaxpr, dot_prec)
    io_bytes = sum(_aval_bytes(v.aval) for v in closed.jaxpr.invars)
    io_bytes += sum(_aval_bytes(v.aval) for v in closed.jaxpr.outvars)
    precision = max(dot_prec, key=dot_prec.get) if dot_prec else "bf16"
    return StepCost(flops, nbytes, io_bytes, precision)


# -- device peak --------------------------------------------------------

#: peak FLOP/s per chip by precision class and device_kind substring
#: (vendor datasheet numbers; MFU is relative to THIS). ``bf16`` is
#: the dense bf16-input/f32-accumulate MXU rate every training row
#: uses; ``int8`` is the doubled-throughput 8-bit MXU rate on the
#: generations that have one (v5e/v5p/v6 — v2-v4 run int8 at the bf16
#:  rate); ``fp8`` is native only on v6-class chips, elsewhere fp8
#: matmuls upcast and the honest peak is the bf16 entry (the
#: fallback). A low-precision program scored against the bf16 peak
#: would silently over-report MFU by up to 2x — the reason
#: ``veles_step_mfu_ratio`` resolves its peak per program precision.
_PEAK_FLOPS_BY_KIND = {
    "bf16": (
        ("TPU v6", 918e12),
        ("TPU v5p", 459e12),
        ("TPU v5e", 197e12),
        ("TPU v5 lite", 197e12),
        ("TPU v4", 275e12),
        ("TPU v3", 123e12),
        ("TPU v2", 45e12),
    ),
    "int8": (
        ("TPU v6", 1836e12),
        ("TPU v5p", 918e12),
        ("TPU v5e", 394e12),
        ("TPU v5 lite", 394e12),
    ),
    "fp8": (
        ("TPU v6", 1836e12),
    ),
}

#: per-precision env overrides (the escape hatch for new hardware and
#: deterministic tests); VELES_PEAK_FLOPS keeps its pre-existing
#: meaning = the bf16/default peak
_PEAK_ENV = {"bf16": "VELES_PEAK_FLOPS",
             "int8": "VELES_PEAK_FLOPS_INT8",
             "fp8": "VELES_PEAK_FLOPS_FP8"}


def device_peak_flops(precision="bf16"):
    """Peak FLOP/s of the default device for ``precision`` ("bf16" |
    "int8" | "fp8"), or None when unknown (CPU, unrecognized kind).
    ``$VELES_PEAK_FLOPS`` (and ``_INT8``/``_FP8``) override. A
    precision with no table entry for the device falls back to the
    bf16 row — the rate those matmuls actually run at."""
    env = os.environ.get(_PEAK_ENV.get(precision, "VELES_PEAK_FLOPS"))
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    kind = str(kind).lower()
    for table in (_PEAK_FLOPS_BY_KIND.get(precision, ()),
                  _PEAK_FLOPS_BY_KIND["bf16"]):
        for sub, peak in table:
            if sub.lower() in kind:
                return peak
    return None


# -- the ledger ---------------------------------------------------------


class PerfLedger:
    """Per-program cost cache + the ``veles_step_*`` publisher.

    ``cost()`` analyzes a program once per (program, shape signature)
    key; ``record_dispatch()`` turns (cost, wall seconds, work
    counts) into registry updates. Both are cheap after the first
    call per program, so the per-dispatch overhead is a handful of
    counter ops."""

    def __init__(self):
        self._lock = threading.Lock()
        self._costs = {}
        self._kids = {}

    def cost(self, key, fn, args):
        """The cached :class:`StepCost` for ``key``, analyzing
        ``fn(*args)`` on first sight. Analysis failures degrade to a
        zero cost — accounting must never break a dispatch path.

        Callers key by ``id(fn)``, so each entry holds a weakref to
        its program: a later function reallocated at a freed id must
        re-analyze, not inherit the dead program's cost, and dead
        entries are dropped instead of accumulating forever."""
        with self._lock:
            entry = self._costs.get(key)
            if entry is not None:
                ref, cost = entry
                if ref is None or ref() is fn:
                    return cost
                del self._costs[key]      # id reused by a new program
        t0 = time.perf_counter()
        try:
            cost = program_cost(fn, args)
        except Exception:
            cost = StepCost()
        if telemetry.tracer.active:
            telemetry.tracer.add_complete(
                "perf.analyze", t0, time.perf_counter() - t0,
                flops=cost.flops)
        try:
            ref = weakref.ref(fn)
        except TypeError:
            ref = None                    # plain-callable fallback
        with self._lock:
            # opportunistic sweep: entries whose program died free up
            # with the next analysis instead of growing unboundedly
            dead = [k for k, (r, _) in self._costs.items()
                    if r is not None and r() is None]
            for k in dead:
                del self._costs[k]
            self._costs[key] = (ref, cost)
        return cost

    def sizes(self):
        """Memory-accounting view (``veles/profiling.py`` exports it
        as ``veles_perf_ledger_*`` gauges): live cached programs and
        their summed per-call I/O footprint estimate — a size proxy
        for what the compiled-program cache pins, not an HBM meter."""
        with self._lock:
            entries = list(self._costs.values())
        programs, est = 0, 0.0
        for ref, cost in entries:
            if ref is not None and ref() is None:
                continue                 # program died; sweep pending
            programs += 1
            est += cost.io_bytes
        return {"programs": programs, "est_bytes": est}

    def _children(self, kind):
        with self._lock:
            kids = self._kids.get(kind)
            if kids is None:
                kids = self._kids[kind] = {
                    "flops": telemetry.LazyChild(
                        lambda k=kind: telemetry.counter(
                            "veles_step_flops_total",
                            "Arithmetic performed by compiled step "
                            "programs (jaxpr-derived)",
                            ("kind",)).labels(k)),
                    "bytes": telemetry.LazyChild(
                        lambda k=kind: telemetry.counter(
                            "veles_step_bytes_total",
                            "Equation-output bytes of compiled step "
                            "programs (memory-traffic proxy)",
                            ("kind",)).labels(k)),
                    "fps": telemetry.LazyChild(
                        lambda k=kind: telemetry.gauge(
                            "veles_step_flops_per_second",
                            "Achieved FLOP/s of the latest dispatch",
                            ("kind",)).labels(k)),
                    "mfu": telemetry.LazyChild(
                        lambda k=kind: telemetry.gauge(
                            "veles_step_mfu_ratio",
                            "Achieved FLOP/s over the device peak "
                            "(VELES_PEAK_FLOPS overrides the table)",
                            ("kind",)).labels(k)),
                    "sps": telemetry.LazyChild(
                        lambda k=kind: telemetry.gauge(
                            "veles_step_samples_per_second",
                            "Samples consumed per second by the "
                            "latest dispatch", ("kind",)).labels(k)),
                    "tps": telemetry.LazyChild(
                        lambda k=kind: telemetry.gauge(
                            "veles_step_tokens_per_second",
                            "Tokens consumed per second by the "
                            "latest dispatch (LM loaders)",
                            ("kind",)).labels(k)),
                }
        return kids

    def record_dispatch(self, kind, cost, seconds, samples=None,
                        tokens=None):
        """Account one completed dispatch of a program costing
        ``cost`` per call that took ``seconds`` wall time and
        consumed ``samples``/``tokens`` of data."""
        kids = self._children(kind)
        if cost is not None and cost.flops:
            kids["flops"].get().inc(cost.flops)
            if seconds > 0:
                fps = cost.flops / seconds
                kids["fps"].get().set(fps)
                peak = device_peak_flops(
                    getattr(cost, "precision", None) or "bf16")
                if peak:
                    kids["mfu"].get().set(fps / peak)
        if cost is not None and cost.bytes:
            kids["bytes"].get().inc(cost.bytes)
        if seconds > 0:
            if samples:
                kids["sps"].get().set(samples / seconds)
            if tokens:
                kids["tps"].get().set(tokens / seconds)


#: process-wide ledger (mirrors the telemetry registry's stance: one
#: spine, views on top)
ledger = PerfLedger()
