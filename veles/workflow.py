"""Workflow: a unit container and gated-DAG driver.

Re-design of ``veles/workflow.py`` [U] (SURVEY.md §2.1 "Workflow",
§3.1/§3.2 call stacks). The workflow owns ``start_point`` / ``end_point``
units; ``run()`` fires the start point and keeps scheduling units whose
incoming open links have all signalled, until the end point runs (the
training loop is a *cycle* in the graph, re-entered until Decision opens
the gate into the end point — SURVEY.md §1 "Key architectural fact").

The reference drove this with a thread pool; here the scheduler is a
deterministic single-threaded worklist (see rationale in
``veles/units.py``). A workflow is itself a :class:`Unit` so workflows
nest, and it aggregates per-unit timing into the profiling report.
"""

import sys
import time
from collections import deque

from veles import telemetry
from veles.units import Unit, TrivialUnit, Container


class StartPoint(TrivialUnit):
    pass


class EndPoint(TrivialUnit):  # zlint: disable=checkpoint-state (reached is a per-run completion flag, re-derived by the scheduler every run)
    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.reached = False

    def run(self):
        self.reached = True


class Workflow(Unit, Container):
    """Container of units + graph driver."""

    def __init__(self, workflow=None, name=None, **kwargs):
        self._units = []
        super().__init__(workflow, name=name, **kwargs)
        self.start_point = StartPoint(self, name="start_point")
        self.end_point = EndPoint(self, name="end_point")
        self._stopped = False
        self.run_number = 0

    # -- container ----------------------------------------------------

    def add_unit(self, unit: Unit):
        if unit in self._units:
            return
        # Uniquify names: params/state pytrees, FlowContext routing and
        # the distribution registry are all keyed by unit.name, so two
        # same-named units would silently collide.
        base = unit.name
        taken = {u.name for u in self._units}
        if unit.name in taken:
            i = 2
            while "%s_%d" % (base, i) in taken:
                i += 1
            unit.name = "%s_%d" % (base, i)
        self._units.append(unit)
        unit.workflow = self

    def del_unit(self, unit: Unit):
        if unit in self._units:
            self._units.remove(unit)
            unit.unlink_all()
            unit.workflow = None

    @property
    def units(self):
        return list(self._units)

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    # -- lifecycle ----------------------------------------------------

    def initialize(self, **kwargs):
        """Initialize in (cycle-tolerant) topological order so producers
        resolve shapes before consumers (§3.1). Kahn's algorithm over
        the control edges; units stuck on cycle back-edges are released
        in discovery order."""
        super().initialize(**kwargs)
        order = self.topo_order()
        for unit in order:
            if unit is not self:
                unit.initialize(**kwargs)
        return order

    def topo_order(self):
        """Cycle-tolerant topological order of all units, start_point
        first; unreachable units (plotters linked later) at the end."""
        indeg = {id(u): 0 for u in self._units}
        for u in self._units:
            for dst in u.links_to:
                if id(dst) in indeg:
                    indeg[id(dst)] += 1
        ready = deque(u for u in self._units if indeg[id(u)] == 0)
        order, seen = [], set()
        pending = set(indeg) - {id(u) for u in ready}
        while ready or pending:
            if not ready:
                # Cycle: release the earliest-added pending unit.
                for u in self._units:
                    if id(u) in pending:
                        ready.append(u)
                        pending.discard(id(u))
                        break
            unit = ready.popleft()
            if id(unit) in seen:
                continue
            seen.add(id(unit))
            order.append(unit)
            for dst in unit.links_to:
                if id(dst) in indeg and id(dst) not in seen:
                    indeg[id(dst)] -= 1
                    if indeg[id(dst)] <= 0 and id(dst) in pending:
                        pending.discard(id(dst))
                        ready.append(dst)
        return order

    def run(self):
        """Drive the gated DAG until end_point runs or stop() is called.

        Timing note: run_time/run_calls are updated by Unit._execute
        when this workflow is nested inside another; a top-level run is
        timed by the caller (Launcher) — updating here as well would
        double-count nested workflows in print_stats.
        """
        self._stopped = False
        self.end_point.reached = False
        self.run_number += 1
        run_start = time.perf_counter()
        # Clear stale fired-link flags from a previous stopped run: a
        # leftover True on a fan-in unit would let it fire early.
        for unit in self._units:
            unit._clear_inbox()
        worklist = deque(self.start_point._execute())
        while worklist and not self._stopped:
            unit = worklist.popleft()
            if unit is self.end_point:
                # End point still honours the all-links rule.
                if unit._ready():
                    unit._execute()
                    break
                continue
            if unit._ready():
                worklist.extend(unit._execute())
        if telemetry.tracer.active:
            telemetry.tracer.add_complete(
                "workflow.run", run_start,
                time.perf_counter() - run_start, workflow=self.name,
                run_number=self.run_number)

    def stop(self):
        self._stopped = True
        for unit in self._units:
            if unit is not self:
                unit.stop()

    # -- checkpoint / resume (generic fallback) ------------------------

    def checkpoint_state(self):
        """Generic resumable state: every unit exposing ``get_state``
        contributes under its name. NNWorkflow overrides this with the
        richer params/optimizer tree; this fallback makes ANY workflow
        (custom unit graphs driven straight through Launcher) at least
        preemption-checkpointable."""
        tree = {"units": {}, "meta": {"workflow": self.name,
                                      "run_number": self.run_number}}
        for unit in self._units:
            get = getattr(unit, "get_state", None)
            if callable(get):
                state = get()
                if state:
                    tree["units"][unit.name] = state
        return tree

    def restore_state(self, tree):
        for name, state in tree.get("units", {}).items():
            try:
                unit = self.unit_by_name(name)
            except KeyError:
                self.warning("checkpoint names unknown unit %r — "
                             "skipped", name)
                continue
            setter = getattr(unit, "set_state", None)
            if callable(setter):
                setter(state)

    # -- introspection / observability --------------------------------

    def generate_graph(self) -> str:
        """Graphviz dot dump of the unit DAG (the reference's
        ``--workflow-graph``; SURVEY.md §5.1)."""
        lines = ["digraph %s {" % self.name.replace(" ", "_"),
                 "  rankdir=TB;"]
        index = {unit: "u%d" % i for i, unit in enumerate(self._units)}
        for unit, uid in index.items():
            shape = "oval" if isinstance(unit, TrivialUnit) else "box"
            lines.append('  %s [label="%s\\n%s" shape=%s];'
                         % (uid, unit.name, type(unit).__name__, shape))
        for unit, uid in index.items():
            for dst in unit.links_to:
                if dst in index:
                    lines.append("  %s -> %s;" % (uid, index[dst]))
        lines.append("}")
        return "\n".join(lines)

    def print_stats(self, stream=sys.stderr):
        """Per-unit wall-time table (SURVEY.md §5.1)."""
        rows = sorted(((u.run_time, u.run_calls, u.name)
                       for u in self._units if u.run_calls),
                      reverse=True)
        total = sum(r[0] for r in rows) or 1e-12
        stream.write("%-32s %10s %8s %7s\n"
                     % ("unit", "time(s)", "calls", "share"))
        for t, calls, name in rows:
            stream.write("%-32s %10.4f %8d %6.1f%%\n"
                         % (name, t, calls, 100.0 * t / total))

    def print_unit_sizes(self, stream=sys.stderr):
        """Per-unit Array buffer footprint (the reference's
        ``--dump-unit-sizes`` [U?]; SURVEY.md §5.1)."""
        from veles.memory import Array
        rows = []
        seen = set()   # linked Arrays are shared: count each buffer once
        for u in self._units:
            total = 0
            for value in vars(u).values():
                # Array.nbytes skips the map-state check: a device-
                # dirty (UNMAPPED) param Array would make .mem raise
                if isinstance(value, Array) and value \
                        and id(value) not in seen:
                    seen.add(id(value))
                    total += value.nbytes
            if total:
                rows.append((total, u.name))
        rows.sort(reverse=True)
        stream.write("%-32s %12s\n" % ("unit", "bytes"))
        for nbytes, name in rows:
            stream.write("%-32s %12d\n" % (name, nbytes))
        stream.write("%-32s %12d\n"
                     % ("TOTAL", sum(r[0] for r in rows)))

    def unit_by_name(self, name: str) -> Unit:
        for unit in self._units:
            if unit.name == name:
                return unit
        raise KeyError(name)
