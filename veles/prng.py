"""Seeded deterministic PRNG facade.

Re-design of ``veles/prng/random_generator.py`` [U] (SURVEY.md §2.1
"PRNG"). The reference keeps a registry of named, seeded generators so
runs are exactly reproducible and the CLI can seed them from files/specs.

TPU translation (SURVEY.md §7 "Exact-parity RNG"): the **numpy** side
(weight init, shuffling, oracle dropout) uses ``numpy.random.Generator``
and defines golden values bitwise; the **jax** side threads
``jax.random`` keys through the step state and matches the oracle only
statistically (convergence), never bitwise.
"""

import hashlib

import numpy

_generators = {}


class RandomGenerator:
    """A named, seedable wrapper over ``numpy.random.Generator`` with the
    handful of draws the framework uses."""

    def __init__(self, key: str, seed=None):
        self.key = key
        self.seed(seed if seed is not None else self._default_seed(key))

    @staticmethod
    def _default_seed(key: str) -> int:
        # Stable across processes/pythons (unlike hash()).
        return int.from_bytes(
            hashlib.sha256(key.encode()).digest()[:4], "little")

    def seed(self, seed) -> None:
        self._seed = int(seed)
        self._gen = numpy.random.Generator(numpy.random.PCG64(self._seed))

    @property
    def state_seed(self) -> int:
        return self._seed

    # -- draws --------------------------------------------------------

    def fill_uniform(self, arr: numpy.ndarray, vmin=-1.0, vmax=1.0):
        arr[...] = self._gen.uniform(vmin, vmax, size=arr.shape) \
            .astype(arr.dtype)

    def fill_normal(self, arr: numpy.ndarray, mean=0.0, stddev=1.0):
        arr[...] = self._gen.normal(mean, stddev, size=arr.shape) \
            .astype(arr.dtype)

    def uniform(self, vmin, vmax, shape, dtype=numpy.float32):
        return self._gen.uniform(vmin, vmax, size=shape).astype(dtype)

    def normal(self, mean, stddev, shape, dtype=numpy.float32):
        return self._gen.normal(mean, stddev, size=shape).astype(dtype)

    def permutation(self, n: int) -> numpy.ndarray:
        return self._gen.permutation(n)

    def randint(self, low, high=None, size=None):
        return self._gen.integers(low, high, size=size)

    def random_sample(self, shape) -> numpy.ndarray:
        return self._gen.random(size=shape, dtype=numpy.float64)

    def jax_key(self):
        """Derive a jax PRNG key from this generator's seed (lazy import
        so the oracle path never touches jax)."""
        import jax
        return jax.random.PRNGKey(self._seed)


def get(key: str = "default") -> RandomGenerator:
    """Registry access, mirroring ``veles.prng.get`` [U]."""
    gen = _generators.get(key)
    if gen is None:
        seed = None if _master_seed is None \
            else _key_seed(_master_seed, key)
        gen = _generators[key] = RandomGenerator(key, seed)
    return gen


_master_seed = None


def _key_seed(master: int, key: str) -> int:
    return (master * 1000003 + RandomGenerator._default_seed(key)) \
        % (2 ** 63)


def seed_all(seed: int) -> None:
    """Re-seed every registered generator deterministically from one
    master seed (CLI ``--seed`` behaviour). Per-key seeds derive from
    the key *name* so results don't depend on registration order; later
    ``get()`` of a fresh key under the same master seed is deterministic
    too."""
    global _master_seed
    _master_seed = int(seed)
    for key, gen in _generators.items():
        gen.seed(_key_seed(_master_seed, key))
