"""Single-threaded selector reactor: the shared event loop under the
training wire and the HTTP planes.

ROADMAP item 3's second half (the first — compressed gradient sync —
shipped in PR 7): the master used to burn one blocking thread per
slave connection parked in ``recv``, plus one HTTP thread per probe
request, so the master's connection ceiling was thread scheduling and
GIL contention, not the network. This module replaces all of that
with ONE daemon loop thread per process owning every non-blocking
socket through a :mod:`selectors` selector:

* :class:`Reactor` — the loop: readiness dispatch, a timer heap
  (``call_later``/``every`` — heartbeat and lease-timeout sweeps),
  and a thread-safe ``call_soon`` handoff (a wakeup socketpair) so
  other threads can schedule work onto the loop without touching a
  socket themselves;
* :class:`Connection` — one non-blocking socket: incremental reads,
  and a per-connection bounded WRITE QUEUE with an optimistic
  fast path (most frames fit the kernel buffer in one ``send``).
  Backpressure is per connection: a slow reader accumulates queue up
  to ``max_write_buffer`` and is then dropped with a counted fault —
  it can never block the loop, the merge path, or other connections;
* :class:`HttpServer` / :class:`HttpConnection` — a minimal HTTP/1.1
  surface ON the loop: probe/metrics routes answer inline from
  cached state (no thread per request — the zlint ``probe-purity``
  contract), while routes that must block (``/v1/predict`` parking
  in the micro-batcher, dashboard provider pulls) are handed to a
  worker thread which replies through ``call_soon``. Chunked
  transfer-encoding (``HttpRequest.begin_stream`` ->
  :class:`HttpStream`) carries streaming responses — per-token
  ``/v1/generate`` chunks — through the same bounded write queue, so
  a stalled stream reader overflows and drops exactly like a stalled
  weight-broadcast consumer, with an ``on_close`` hook telling the
  producer to stop.

The frame PROTOCOL stays in ``veles/server.py`` (``FramedConnection``
there subclasses :class:`Connection`); this module knows nothing
about pickles or HMAC.

Callback discipline (enforced by the zlint ``reactor-purity`` rule):
code running on the loop — ``on_frame``/``on_timer`` methods and
``call_soon``/``call_later``/``every`` targets — must never call
blocking primitives (raw-socket ``recv``/``sendall``/``accept``,
``time.sleep``, ``Event.wait``/``Thread.join``, ``urlopen``). Taking
the existing short-lived locks (the master's request lock) is fine —
that is the same serialization the thread-per-connection design had —
but anything that can park the loop parks EVERY connection and every
probe with it.

What deliberately stays OFF the loop: XLA dispatch and device compute
(the slave side), the master's persist thread (store I/O), the health
monitor's sampler (checks may take locks and scan registries), and
blocking HTTP routes as above. The loop owns sockets; threads own
waiting.

Instrumentation: ``veles_reactor_loop_lag_seconds`` (how late the
loop fires a due timer — the "is the loop healthy" number readiness
checks and ``velescli top`` read), ``veles_reactor_connections``, and
``veles_reactor_overflow_drops_total``.
"""

import collections
import heapq
import json
import selectors
import socket
import threading
import time

from veles import telemetry
from veles.logger import Logger

#: per-connection write-queue cap (bytes) before the peer is declared
#: a dead reader and dropped: several full MNIST-scale weight
#: broadcasts, far above anything a healthy consumer accumulates
DEFAULT_MAX_WRITE_BUFFER = 64 << 20

#: bytes one connection may consume per readable event before the
#: loop moves on — keeps a firehose peer from starving the others
#: (the selector is level-triggered, so the remainder re-fires)
READ_BUDGET = 1 << 18

_G_LAG = telemetry.LazyChild(lambda: telemetry.gauge(
    "veles_reactor_loop_lag_seconds",
    "How late the reactor fired its periodic lag probe — sustained "
    "lag means a callback is blocking the shared loop"))
_G_CONNS = telemetry.LazyChild(lambda: telemetry.gauge(
    "veles_reactor_connections",
    "Sockets currently owned by the reactor loop"))
_C_OVERFLOW = telemetry.LazyChild(lambda: telemetry.counter(
    "veles_reactor_overflow_drops_total",
    "Connections dropped because their bounded write queue exceeded "
    "max_write_buffer (slow/stalled reader)"))


class Timer:
    """Handle for one scheduled callback; ``interval`` re-arms it."""

    __slots__ = ("due", "interval", "fn", "args", "cancelled")

    def __init__(self, due, fn, args, interval=None):
        self.due = due
        self.fn = fn
        self.args = args
        self.interval = interval
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


class Reactor(Logger):
    """The loop. One per process (see :func:`get_reactor`); servers
    register sockets, timers and ``call_soon`` thunks on it."""

    #: cadence of the self-lag probe (also the idle select timeout cap)
    LAG_PROBE_INTERVAL = 0.25

    def __init__(self, name="reactor"):
        self.name = name
        self._selector = selectors.DefaultSelector()
        self._soon = collections.deque()
        self._timers = []               # heap of (due, seq, Timer)
        self._seq = 0
        self._lock = threading.Lock()   # thread start + seq
        self._thread = None
        self._tid = None
        self._stopped = False
        self._n_conns = 0
        #: seconds the last lag probe fired behind schedule — the
        #: loop's own self-measurement (exported as the loop-lag
        #: gauge). A WEDGED loop cannot update this, so readiness
        #: checks must read :meth:`current_lag`, not this attribute.
        self.loop_lag_s = 0.0
        #: monotonic time the lag probe last fired (any-thread read)
        self.last_probe = time.monotonic()
        # wakeup channel: call_soon from another thread writes one
        # byte so a parked select() returns immediately
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ,
                                _Waker(self._wake_r))

    # -- lifecycle -----------------------------------------------------

    def ensure_started(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stopped = False
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name=self.name)
                self._thread.start()
        return self

    @property
    def alive(self):
        thread = self._thread
        return thread is not None and thread.is_alive()

    def in_loop(self):
        return threading.get_ident() == self._tid

    def current_lag(self):
        """Loop lag as observable from ANY thread: the loop's own
        last self-measurement — or, when the loop is wedged behind a
        blocking callback and cannot even run its probe, how overdue
        that probe is. Readiness checks must use this, never
        ``loop_lag_s`` alone (a frozen loop holds its last near-zero
        value forever)."""
        overdue = time.monotonic() - self.last_probe \
            - self.LAG_PROBE_INTERVAL
        return max(self.loop_lag_s, overdue, 0.0)

    def stop(self):
        """Stop the loop thread (tests); registered sockets are NOT
        closed — their owners hold them."""
        self._stopped = True
        self._wakeup()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)

    # -- scheduling (thread-safe) --------------------------------------

    def call_soon(self, fn, *args):
        """Run ``fn(*args)`` on the loop as soon as possible. The ONE
        correct way for another thread to touch loop-owned state."""
        self._soon.append((fn, args))
        self._wakeup()

    def call_later(self, delay, fn, *args):
        """Run ``fn(*args)`` on the loop after ``delay`` seconds;
        -> cancellable :class:`Timer`."""
        timer = Timer(time.monotonic() + max(delay, 0.0), fn, args)
        self._push_timer(timer)
        return timer

    def every(self, interval, fn, *args):
        """Run ``fn(*args)`` on the loop every ``interval`` seconds
        (re-armed AFTER each firing — no overlap); -> :class:`Timer`."""
        interval = max(float(interval), 1e-3)
        timer = Timer(time.monotonic() + interval, fn, args,
                      interval=interval)
        self._push_timer(timer)
        return timer

    def post(self, fn, *args):
        """Run ``fn`` now when already on the loop, else hand it off
        via :meth:`call_soon` (the reply path worker threads use)."""
        if self.in_loop():
            fn(*args)
        else:
            self.call_soon(fn, *args)

    def _push_timer(self, timer):
        if self.in_loop():
            self._seq += 1
            heapq.heappush(self._timers, (timer.due, self._seq, timer))
        else:
            self.call_soon(self._push_timer, timer)

    def _wakeup(self):
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, InterruptedError):
            pass                # a full pipe already guarantees wakeup
        except OSError:
            pass                # reactor being torn down

    # -- socket registration (loop thread only) ------------------------

    def register(self, sock, events, handler):
        self._selector.register(sock, events, handler)

    def modify(self, sock, events, handler):
        self._selector.modify(sock, events, handler)

    def unregister(self, sock):
        try:
            self._selector.unregister(sock)
        except (KeyError, ValueError):
            pass

    def add_acceptor(self, sock, factory):
        """Register a LISTENING socket: ``factory(conn_sock, addr)``
        runs on the loop per accepted connection. Thread-safe (defers
        to the loop); the kernel backlog holds early connects."""
        acceptor = _Acceptor(self, sock, factory)
        self.post(self.register, sock, selectors.EVENT_READ, acceptor)
        return acceptor

    def _conn_opened(self):
        self._n_conns += 1
        _G_CONNS.get().set(self._n_conns)

    def _conn_closed(self):
        self._n_conns -= 1
        _G_CONNS.get().set(self._n_conns)

    # -- the loop ------------------------------------------------------

    def _run(self):
        self._tid = threading.get_ident()
        self.last_probe = time.monotonic()
        lag_due = self.last_probe + self.LAG_PROBE_INTERVAL
        while not self._stopped:
            now = time.monotonic()
            if now >= lag_due:
                # the probe is the lag INSTRUMENT: how far behind
                # schedule the loop is running right now
                self.loop_lag_s = now - lag_due
                self.last_probe = now
                _G_LAG.get().set(self.loop_lag_s)
                lag_due = now + self.LAG_PROBE_INTERVAL
            timeout = lag_due - now
            if self._timers:
                timeout = min(timeout,
                              max(self._timers[0][0] - now, 0.0))
            if self._soon:
                timeout = 0.0
            try:
                events = self._selector.select(timeout)
            except OSError:
                # a socket closed out from under the selector between
                # callbacks: retry — unregister already happened
                continue
            for key, mask in events:
                handler = key.data
                try:
                    if mask & selectors.EVENT_READ:
                        handler.on_readable()
                    if mask & selectors.EVENT_WRITE:
                        handler.on_writable()
                except Exception as exc:
                    # a callback must never kill the shared loop
                    self.warning("reactor handler %r failed: %s: %s",
                                 handler, type(exc).__name__, exc)
                    closer = getattr(handler, "close", None)
                    if closer is not None:
                        try:
                            closer(reason="handler error: %s" % exc)
                        except Exception:
                            pass
            self._fire_timers()
            self._drain_soon()
        self._tid = None

    def _fire_timers(self):
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, _, timer = heapq.heappop(self._timers)
            if timer.cancelled:
                continue
            lag = now - timer.due
            if lag > self.loop_lag_s:
                self.loop_lag_s = lag
                _G_LAG.get().set(lag)
            try:
                timer.fn(*timer.args)
            except Exception as exc:
                self.warning("reactor timer %r failed: %s: %s",
                             timer.fn, type(exc).__name__, exc)
            if timer.interval is not None and not timer.cancelled:
                timer.due = time.monotonic() + timer.interval
                self._seq += 1
                heapq.heappush(self._timers,
                               (timer.due, self._seq, timer))

    def _drain_soon(self):
        # bounded batch: a callback that re-posts itself must not
        # starve socket readiness forever
        for _ in range(len(self._soon)):
            try:
                fn, args = self._soon.popleft()
            except IndexError:
                return
            try:
                fn(*args)
            except Exception as exc:
                self.warning("call_soon %r failed: %s: %s", fn,
                             type(exc).__name__, exc)


class _Waker:
    """Drains the wakeup socketpair (the bytes only exist to unpark
    ``select``)."""

    __slots__ = ("_sock",)

    def __init__(self, sock):
        self._sock = sock

    def on_readable(self):
        try:
            while self._sock.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass


class _Acceptor:
    """Readiness handler for one listening socket."""

    __slots__ = ("reactor", "sock", "factory", "closed")

    def __init__(self, reactor, sock, factory):
        self.reactor = reactor
        self.sock = sock
        self.factory = factory
        self.closed = False

    def on_readable(self):
        for _ in range(64):             # accept bursts, stay fair
            try:
                sock, addr = self.sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return                  # listener closed under us
            sock.setblocking(False)
            try:
                self.factory(sock, addr)
            except Exception as exc:
                # a failing factory costs THIS connection only: the
                # error must never escape to the loop's handler-error
                # recovery, which would close() this acceptor and
                # silently stop the listener forever
                self.reactor.warning(
                    "accept factory failed for %s: %s: %s", addr,
                    type(exc).__name__, exc)
                try:
                    sock.close()
                except OSError:
                    pass

    def close(self, reason=None):
        if self.closed:
            return
        self.closed = True
        self.reactor.unregister(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass


class Connection:
    """One non-blocking socket owned by the reactor.

    Subclasses implement ``data_received(bytes)`` (or override
    :meth:`on_readable` for zero-copy assembly) and ``on_closed``.
    All methods are LOOP-THREAD ONLY unless stated otherwise."""

    CHUNK = 1 << 16

    def __init__(self, reactor, sock, max_write_buffer=None):
        sock.setblocking(False)
        try:
            # request/response frames must not wait out Nagle; no-op
            # for non-TCP sockets (tests use socketpairs)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.reactor = reactor
        self.sock = sock
        self.max_write_buffer = max_write_buffer \
            or DEFAULT_MAX_WRITE_BUFFER
        self._wq = collections.deque()
        #: queued-but-unsent bytes — read (racily, for display) by
        #: status surfaces on other threads; written on the loop only
        self.write_queued = 0
        self.closed = False
        self.close_reason = None
        self._events = selectors.EVENT_READ
        self._close_when_drained = False
        self.last_recv = time.monotonic()
        reactor.register(sock, self._events, self)
        reactor._conn_opened()

    # -- reading -------------------------------------------------------

    def on_readable(self):
        budget = READ_BUDGET
        while budget > 0 and not self.closed:
            try:
                data = self.sock.recv(min(self.CHUNK, budget))
            except (BlockingIOError, InterruptedError):
                return
            except OSError as exc:
                self.close(reason="recv: %s" % exc)
                return
            if not data:
                self.close(reason="eof")
                return
            budget -= len(data)
            self.last_recv = time.monotonic()
            self.data_received(data)

    def data_received(self, data):
        raise NotImplementedError

    # -- writing -------------------------------------------------------

    def send_parts(self, parts):
        """Write a sequence of bytes-like parts, in order, without
        ever blocking: an optimistic direct ``send`` while the queue
        is empty (the common case — no copy), then the REMAINDER is
        copied into the bounded queue. The copy is deliberate: queued
        buffers may alias live arrays (weight broadcasts) that the
        very next merge mutates, and a queued view would then ship
        corrupt bytes under an already-computed HMAC."""
        if self.closed:
            return
        parts = [memoryview(p).cast("B") for p in parts]
        i = 0
        if not self._wq:
            try:
                while i < len(parts):
                    sent = self.sock.send(parts[i])
                    if sent < len(parts[i]):
                        parts[i] = parts[i][sent:]
                        break
                    i += 1
            except (BlockingIOError, InterruptedError):
                pass
            except OSError as exc:
                self.close(reason="send: %s" % exc)
                return
        if i >= len(parts):
            return
        for part in parts[i:]:
            blob = bytes(part)
            self._wq.append(memoryview(blob))
            self.write_queued += len(blob)
        if self.write_queued > self.max_write_buffer:
            _C_OVERFLOW.get().inc()
            self.close(reason="overflow")
            return
        self._want_write(True)

    def on_writable(self):
        while self._wq and not self.closed:
            buf = self._wq[0]
            try:
                sent = self.sock.send(buf)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as exc:
                self.close(reason="send: %s" % exc)
                return
            self.write_queued -= sent
            if sent == len(buf):
                self._wq.popleft()
            else:
                self._wq[0] = buf[sent:]
                return
        if not self._wq:
            self._want_write(False)
            if self._close_when_drained:
                self.close(reason="drained")

    def close_when_drained(self):
        """Close once the write queue empties (polite goodbyes)."""
        if not self._wq:
            self.close(reason="drained")
        else:
            self._close_when_drained = True

    def _want_write(self, want):
        events = selectors.EVENT_READ \
            | (selectors.EVENT_WRITE if want else 0)
        if events != self._events and not self.closed:
            self._events = events
            self.reactor.modify(self.sock, events, self)

    # -- teardown ------------------------------------------------------

    def close(self, reason=None):
        if self.closed:
            return
        self.closed = True
        self.close_reason = reason
        self.reactor.unregister(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
        self._wq.clear()
        self.write_queued = 0
        self.reactor._conn_closed()
        try:
            self.on_closed(reason)
        except Exception:
            pass

    def on_closed(self, reason):
        pass


class ListeningServer(Logger):
    """Shared listener plumbing for reactor-hosted servers: bind +
    listen + (deferrable) acceptor registration, tracked connections,
    and the cross-thread teardown dance — one implementation for the
    framed wire plane and the HTTP plane. Subclasses implement
    ``build_connection(sock, addr)`` (loop thread)."""

    def __init__(self, address, name="listener", reactor=None,
                 start=True):
        self.name = name
        self.reactor = reactor or get_reactor()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(address)
        sock.listen(128)
        sock.setblocking(False)
        self.socket = sock
        self.server_address = sock.getsockname()
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._acceptor = None
        self._closed = False
        if start:
            self.start()

    def start(self):
        """Register the acceptor on the loop (``start=False`` defers
        this so a caller can finish wiring state the connections
        read — the port is already bound, the kernel backlog holds
        early connects)."""
        if self._acceptor is None and not self._closed:
            self._acceptor = self.reactor.add_acceptor(
                self.socket, self._accept)
        return self

    def build_connection(self, sock, addr):
        raise NotImplementedError

    def _accept(self, sock, addr):
        conn = self.build_connection(sock, addr)
        if conn is not None:
            with self._conns_lock:
                self._conns.add(conn)

    def untrack(self, conn):
        with self._conns_lock:
            self._conns.discard(conn)

    def connections(self):
        with self._conns_lock:
            return list(self._conns)

    @property
    def accepting(self):
        """True while the listener can still accept — False once
        closed OR if the acceptor was torn down out-of-band (the
        readiness checks read this)."""
        acceptor = self._acceptor
        return not self._closed and acceptor is not None \
            and not acceptor.closed

    def on_close_loop(self):
        """Loop-thread hook run during close, before connections are
        severed (cancel timers etc.)."""

    def close(self):
        """Unregister + close listener and live connections; safe
        from any thread, idempotent."""
        if self._closed:
            return
        self._closed = True
        done = threading.Event()

        def on_loop():
            if self._acceptor is not None:
                self._acceptor.close()
            else:
                try:
                    self.socket.close()
                except OSError:
                    pass
            self.on_close_loop()
            for conn in self.connections():
                conn.close(reason="server closed")
            with self._conns_lock:
                self._conns.clear()
            done.set()

        if self.reactor.in_loop():
            on_loop()
        else:
            self.reactor.call_soon(on_loop)
            if not self.reactor.alive:
                on_loop()               # no loop left: tear down inline
            done.wait(2.0)


# -- HTTP on the loop ---------------------------------------------------

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            500: "Internal Server Error", 503: "Service Unavailable",
            504: "Gateway Timeout"}

#: request head cap: probe/metrics/predict requests are small; a peer
#: streaming an unbounded header is attacking, not probing
MAX_HTTP_HEAD = 1 << 16
MAX_HTTP_BODY = 64 << 20


class HttpRequest:
    """One parsed request + the reply surface handed to routes.

    ``reply*`` may be called from ANY thread (worker handoff): the
    response write is posted back onto the loop."""

    __slots__ = ("conn", "method", "path", "headers", "body")

    def __init__(self, conn, method, path, headers, body):
        self.conn = conn
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body

    @property
    def remote_addr(self):
        """``host:port`` of the requesting peer (what a proxy tier
        writes into ``X-Forwarded-For``), or None for non-INET
        sockets (tests use socketpairs)."""
        peer = getattr(self.conn, "peer", None)
        if isinstance(peer, tuple) and len(peer) >= 2:
            return "%s:%s" % (peer[0], peer[1])
        return None

    def reply(self, code, body, ctype="text/plain", headers=()):
        if isinstance(body, str):
            body = body.encode()
        self.conn.reactor.post(
            self.conn.send_response, code, body, ctype, tuple(headers))

    def reply_json(self, code, doc, headers=()):
        self.reply(code, json.dumps(doc).encode(),
                   "application/json", headers)

    def begin_stream(self, code, ctype="application/x-ndjson",
                     headers=(), on_close=None):
        """Start a chunked (``Transfer-Encoding: chunked``) response;
        -> :class:`HttpStream` whose ``write``/``end`` may be called
        from ANY thread (each chunk is posted onto the loop and rides
        the connection's bounded write queue — a stalled reader
        overflows it and is dropped like any other dead peer).
        ``on_close(reason)`` fires ON THE LOOP if the connection dies
        BEFORE :meth:`HttpStream.end` (client disconnect, write-queue
        overflow) — the producer's cue to stop generating; it must
        not block."""
        conn = self.conn
        conn.reactor.post(conn.start_stream, code, ctype,
                          tuple(headers), on_close)
        return HttpStream(conn)

    def defer(self, fn, *args):
        """Run ``fn(*args)`` on a fresh worker thread — the escape
        hatch for routes that must block (predict parking in the
        micro-batcher, dashboard provider pulls). ``fn`` replies via
        this request; an exception becomes a 500."""
        def run():
            try:
                fn(*args)
            except Exception as exc:
                self.reply_json(500, {"error": "%s: %s"
                                      % (type(exc).__name__, exc)})
        threading.Thread(target=run, daemon=True,
                         name="http-worker").start()


class HttpStream:
    """Thread-safe handle for one in-flight chunked response (see
    :meth:`HttpRequest.begin_stream`). Writes after the peer dropped
    are silently discarded — the producer learns of the death through
    the ``on_close`` callback (or by reading :attr:`closed`)."""

    __slots__ = ("conn",)

    def __init__(self, conn):
        self.conn = conn

    @property
    def closed(self):
        return self.conn.closed

    def write(self, data):
        """Queue one chunk (bytes or str)."""
        if isinstance(data, str):
            data = data.encode()
        if data:
            self.conn.reactor.post(self.conn.send_chunk, data)

    def end(self):
        """Terminal chunk + drain + close (the normal finish — the
        ``on_close`` callback does NOT fire for it)."""
        self.conn.reactor.post(self.conn.finish_stream)


class HttpConnection(Connection):
    """Incremental HTTP/1.1 request parsing on the loop; one request
    per connection (every response carries ``Connection: close`` —
    probes and scrapes open fresh connections anyway)."""

    def __init__(self, reactor, sock, handler, server=None):
        self._handler = handler
        self._server = server
        try:
            #: peer address as accepted — read by HttpRequest.remote_addr
            self.peer = sock.getpeername()
        except OSError:
            self.peer = None
        self._buf = bytearray()
        self._head = None               # (method, path, headers)
        self._need_body = 0
        self._dispatched = False
        #: fires on close while a chunked response is mid-stream —
        #: cleared by finish_stream, so a NORMAL end never reports a
        #: disconnect (see HttpRequest.begin_stream)
        self._stream_on_close = None
        super().__init__(reactor, sock)

    def on_closed(self, reason):
        if self._server is not None:
            self._server.untrack(self)
        cb = self._stream_on_close
        self._stream_on_close = None
        if cb is not None:
            try:
                cb(reason)
            except Exception:
                pass

    def data_received(self, data):
        if self._dispatched:
            return                      # one request per connection
        self._buf += data
        if self._head is None:
            end = self._buf.find(b"\r\n\r\n")
            if end < 0:
                if len(self._buf) > MAX_HTTP_HEAD:
                    self.close(reason="oversized request head")
                return
            try:
                head = bytes(self._buf[:end]).decode("latin-1")
                del self._buf[:end + 4]
                lines = head.split("\r\n")
                method, path, _version = lines[0].split(" ", 2)
                headers = {}
                for line in lines[1:]:
                    key, _, value = line.partition(":")
                    headers[key.strip().lower()] = value.strip()
                # inside the guard: a garbled/negative Content-Length
                # must answer 400, not leak a ValueError that tears
                # the connection down with no HTTP response
                need = int(headers.get("content-length") or 0)
                if need < 0:
                    raise ValueError("negative content-length")
            except ValueError:
                self.send_response(400, b'{"error": "bad request"}',
                                   "application/json", ())
                return
            self._head = (method.upper(), path, headers)
            self._need_body = need
            if self._need_body > MAX_HTTP_BODY:
                self.close(reason="oversized request body")
                return
        if len(self._buf) < self._need_body:
            return
        method, path, headers = self._head
        body = bytes(self._buf[:self._need_body])
        self._dispatched = True
        request = HttpRequest(self, method, path, headers, body)
        try:
            self._handler(request)
        except Exception as exc:
            request.reply_json(500, {"error": "%s: %s"
                                     % (type(exc).__name__, exc)})

    def send_response(self, code, body, ctype, headers):
        if self.closed:
            return
        head = ["HTTP/1.1 %d %s" % (code, _REASONS.get(code, "OK")),
                "Content-Type: %s" % ctype,
                "Content-Length: %d" % len(body),
                "Connection: close"]
        head.extend("%s: %s" % kv for kv in headers)
        self.send_parts([("\r\n".join(head) + "\r\n\r\n").encode(),
                         body])
        self.close_when_drained()

    # -- chunked streaming (loop thread; posted via HttpStream) --------

    def start_stream(self, code, ctype, headers, on_close):
        """Response head for a chunked-transfer body (streaming
        decode). No Content-Length — chunks follow until
        finish_stream's terminal chunk."""
        if self.closed:
            # born dead: tell the producer immediately
            if on_close is not None:
                try:
                    on_close(self.close_reason or "closed")
                except Exception:
                    pass
            return
        self._stream_on_close = on_close
        head = ["HTTP/1.1 %d %s" % (code, _REASONS.get(code, "OK")),
                "Content-Type: %s" % ctype,
                "Transfer-Encoding: chunked",
                "Connection: close"]
        head.extend("%s: %s" % kv for kv in headers)
        self.send_parts([("\r\n".join(head) + "\r\n\r\n").encode()])

    def send_chunk(self, data):
        if self.closed or not data:
            return
        self.send_parts([b"%x\r\n" % len(data), data, b"\r\n"])

    def finish_stream(self):
        if self.closed:
            return
        # deliberate end: the close that follows is NOT a disconnect
        self._stream_on_close = None
        self.send_parts([b"0\r\n\r\n"])
        self.close_when_drained()


class HttpServer(ListeningServer):
    """An HTTP listener on the shared reactor. ``handler(request)``
    runs ON THE LOOP — it must reply inline from cached state or
    ``request.defer`` to a worker thread."""

    def __init__(self, host, port, handler, name="http",
                 reactor=None, start=True):
        self._handler = handler
        super().__init__((host, port), name=name, reactor=reactor,
                         start=start)
        self.host, self.port = self.server_address[:2]

    def build_connection(self, sock, _addr):
        return HttpConnection(self.reactor, sock, self._handler,
                              server=self)


# -- process-wide reactor plumbing --------------------------------------

_active_lock = threading.Lock()
_active = None


def get_reactor() -> Reactor:
    """The process's shared loop, created and started on first use —
    the master's wire plane, web-status and the serving frontend all
    register on this one instance."""
    global _active
    with _active_lock:
        if _active is None:
            _active = Reactor()
        reactor = _active
    return reactor.ensure_started()


def peek_reactor():
    """The active reactor WITHOUT creating or starting one — for
    health checks that must OBSERVE the loop, not resurrect it (a
    readiness check that ensure_started()s as a side effect could
    never report a dead loop)."""
    with _active_lock:
        return _active


def set_reactor(reactor):
    """Swap the active reactor (-> the previous one, NOT stopped)."""
    global _active
    with _active_lock:
        previous = _active
        _active = reactor
    return previous
