"""Accelerated units and the graph→jit step compiler.

Re-design of ``veles/accelerated_units.py`` [U] (SURVEY.md §2.1
"Accelerated unit", §7 design stance). The reference dispatched each
unit's ``run`` to ``numpy_run`` / ``ocl_run`` / ``cuda_run`` and launched
one or more hand-written kernels per unit, with host↔device map/unmap
around every launch (§3.2 "Boundary crossings"). The TPU build keeps the
per-unit ``numpy_run`` oracle but replaces the per-unit kernel launches
wholesale: every accelerated unit additionally implements

* ``xla_init()`` — declare parameters/optimizer state (host-side numpy
  values living in its ``Array`` attrs, as the oracle path uses), and
* ``xla_run(ctx)`` — a **pure, jax-traceable** function that reads its
  inputs from a :class:`FlowContext` and writes its outputs back.

:class:`StepCompiler` walks the accelerated subgraph once, calls each
``xla_run`` under ``jax.jit`` tracing, and produces a single fused step
function ``step(params, state, batch, hyper) -> (params, state, outputs)``
— the entire forward/backward/update cycle is ONE XLA computation with
donated buffers, which is what makes this design TPU-native rather than
a port (SURVEY.md §3.2: the reference's per-unit launch overhead is
eliminated by construction).
"""

import time

import numpy

from veles import telemetry
from veles.backends import XLADevice, get_device
from veles.memory import Array
from veles.units import Unit
from veles.workflow import Workflow


def _compile_cache_event(kind, hit, build_seconds=None, start=None):
    """Registry bookkeeping for the step-program cache: hits vs
    (re)builds and the time spent tracing/jitting each program kind
    ('step' / 'epoch' / 'window')."""
    if hit:
        telemetry.counter(
            "veles_xla_cache_hits_total",
            "Compiled-program cache hits", ("kind",)).labels(kind).inc()
        return
    telemetry.counter(
        "veles_xla_cache_misses_total",
        "Compiled-program cache misses (trace + jit builds)",
        ("kind",)).labels(kind).inc()
    telemetry.histogram(
        "veles_xla_build_seconds",
        "Time spent building a step program (trace + jit wrap; XLA "
        "compiles lazily on first dispatch — see "
        "veles_xla_dispatch_seconds{warm=\"0\"})",
        ("kind",)).labels(kind).observe(build_seconds)
    if start is not None:
        telemetry.tracer.add_complete(
            "xla.build.%s" % kind, start, build_seconds, kind=kind)


class AcceleratedUnit(Unit):
    """A unit with a numpy oracle and a pure-jax implementation."""

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.device = None

    # -- lifecycle ----------------------------------------------------

    def initialize(self, device=None, **kwargs):
        super().initialize(**kwargs)
        if device is not None:
            self.device = device
        elif self.device is None and self.workflow is not None:
            self.device = getattr(self.workflow, "device", None)

    def init_vectors(self, *arrays):
        """Reference helper: ensure Arrays are allocated [U]."""
        for arr in arrays:
            if isinstance(arr, Array) and arr:
                arr.map_write()

    # -- backend dispatch ---------------------------------------------

    def run(self):
        """Host-graph execution path: oracle only. The XLA path never
        runs units one-by-one — it executes the compiled step (see
        AcceleratedWorkflow.run_step)."""
        self.numpy_run()

    def numpy_run(self):
        raise NotImplementedError(
            "%s lacks numpy_run" % type(self).__name__)

    # -- XLA contract --------------------------------------------------

    #: Names of Array attrs holding trainable parameters; the compiler
    #: lifts them into the params pytree keyed by unit name.
    PARAMS = ()
    #: Names of Array attrs holding mutable non-trainable state
    #: (momentum accumulators, running stats); lifted into state pytree.
    STATE = ()

    def xla_init(self):
        """Prepare parameter/state Arrays (defaults to nothing)."""

    def xla_run(self, ctx):
        """Pure traced computation; read/write via ctx."""
        raise NotImplementedError(
            "%s lacks xla_run" % type(self).__name__)

    # -- pytree lift/sink ---------------------------------------------

    def export_params(self):
        # copies, not views: callers hold these across in-place numpy
        # updates of the underlying Arrays
        return {name: numpy.array(getattr(self, name).map_read().mem)
                for name in self.PARAMS
                if isinstance(getattr(self, name, None), Array)
                and getattr(self, name)}

    def export_state(self):
        return {name: numpy.array(getattr(self, name).map_read().mem)
                for name in self.STATE
                if isinstance(getattr(self, name, None), Array)
                and getattr(self, name)}

    def import_params(self, tree):
        for name, value in tree.items():
            arr = getattr(self, name, None)
            if isinstance(arr, Array):
                arr.map_write()
                arr.mem = numpy.asarray(value, dtype=arr.dtype
                                        if arr else None)

    #: state restores through the same Array-attr path
    import_state = import_params


class FlowContext:
    """The tracing context handed to each unit's ``xla_run``.

    Holds named tensors produced so far plus this unit's view of the
    params/state pytrees and the PRNG key / train flag. Units read
    inputs (resolved through link_attrs wiring by the unit itself) and
    ``set`` their outputs.
    """

    def __init__(self, compiler, params, state, hyper, key, train,
                 axis_name=None):
        self._compiler = compiler
        self.params = params        # full dict: unit name -> {attr: arr}
        self.state = state
        self.hyper = hyper          # dict of scalar hyperparams (lr, ...)
        self.key = key              # jax PRNG key folded per unit
        self.train = train          # python bool: compile-time variant
        self.axis_name = axis_name  # set when traced under shard_map
        self.values = {}            # (producer_unit_name, attr) -> tensor
        self.outputs = {}           # exported outputs (metrics etc.)
        #: model-health plane (veles/model_health.py): when set, GD
        #: units export their per-layer stat vector as one extra fused
        #: output — a compile-time variant, keyed into the program
        #: caches below. ``stats_stride`` is the IN-GRAPH cadence: the
        #: reduces run under a lax.cond every Nth train step (sentinel
        #: rows otherwise), so the steady-state cost amortizes
        self.collect_stats = bool(
            getattr(compiler, "collect_stats", False)) and train
        self.stats_stride = int(
            getattr(compiler, "stats_stride", 1) or 1)

    # value routing ----------------------------------------------------

    def get(self, unit, attr):
        """Value of ``unit.attr``: a traced tensor if some xla_run
        produced it this trace, else the unit's host Array content as a
        constant (weights come from params instead)."""
        key = (unit.name, attr)
        if key in self.values:
            return self.values[key]
        # Follow link_attrs aliasing: reading a linked attr returns the
        # source object's value; find the real producer.
        src, src_attr = _resolve_link(unit, attr)
        key2 = (src.name, src_attr)
        if key2 in self.values:
            return self.values[key2]
        value = getattr(src, src_attr, None)
        if isinstance(value, Array):
            if not value:
                raise ValueError("unset Array %s.%s read during trace"
                                 % (src.name, src_attr))
            return value.devmem
        return value

    def set(self, unit, attr, tensor):
        self.values[(unit.name, attr)] = tensor
        # Mirror through any alias chain start as well.
        src, src_attr = _resolve_link(unit, attr)
        self.values[(src.name, src_attr)] = tensor

    # params/state ------------------------------------------------------

    def unit_params(self, unit):
        return self.params.get(unit.name, {})

    def unit_state(self, unit):
        return self.state.get(unit.name, {})

    def update_params(self, unit, **kv):
        self.params.setdefault(unit.name, {}).update(kv)

    def update_state(self, unit, **kv):
        self.state.setdefault(unit.name, {}).update(kv)

    def fold_key(self, unit):
        """A per-unit PRNG key, stable across steps via the step key."""
        import jax
        import zlib
        h = zlib.crc32(unit.name.encode()) & 0x7FFFFFFF
        return jax.random.fold_in(self.key, h)

    def export(self, name, tensor):
        """Expose a tensor in the step outputs (metrics, err counts)."""
        self.outputs[name] = tensor

    # collectives -------------------------------------------------------

    def pmean(self, tensor):
        """Cross-replica gradient mean. Under plain ``jit`` with sharded
        batches this is the identity — the batch contraction already
        sums across shards and XLA inserts the all-reduce (SURVEY.md §7
        stage 5). Under ``shard_map`` (explicit-collective mode) it is a
        real ``lax.pmean`` over the data axis."""
        if self.axis_name is None:
            return tensor
        import jax
        return jax.lax.pmean(tensor, self.axis_name)

    @property
    def act_dtype(self):
        """Dtype for tensors flowing BETWEEN units (outputs / err
        flows) — the mixed-precision activation policy. bf16 on TPU by
        default; master weights and solver state stay f32 (see
        ``XLADevice.act_dtype``)."""
        return self._compiler.device.act_dtype

    def dot(self, a, b):
        """MXU-friendly matmul: inputs cast to the device compute dtype
        (bfloat16 on TPU), accumulation in float32."""
        import jax.numpy as jnp
        cd = self._compiler.device.compute_dtype
        return jnp.matmul(a.astype(cd), b.astype(cd),
                          preferred_element_type=jnp.float32)

    def einsum(self, spec, *ops):
        """MXU-friendly einsum: same dtype contract as :meth:`dot`."""
        import jax.numpy as jnp
        cd = self._compiler.device.compute_dtype
        return jnp.einsum(spec, *[o.astype(cd) for o in ops],
                          preferred_element_type=jnp.float32)


def _resolve_link(unit, attr):
    """Follow LinkableAttribute aliases to the producing (unit, attr)."""
    from veles.mutable import LinkableAttribute
    seen = set()
    while True:
        if (id(unit), attr) in seen:
            return unit, attr
        seen.add((id(unit), attr))
        descr = type(unit).__dict__.get(attr)
        if isinstance(descr, LinkableAttribute):
            link = unit.__dict__.get("_linked_" + attr)
            if link is not None:
                unit, attr = link[0], link[1]
                continue
        return unit, attr


def _transform_key(transform):
    """Stable memo-key component for a loader's xla_batch_transform:
    bound methods are re-created per attribute access, so key on the
    owner's identity + function, not on the method object."""
    if transform is None:
        return None
    owner = getattr(transform, "__self__", None)
    func = getattr(transform, "__func__", transform)
    return (id(owner) if owner is not None else id(transform),
            getattr(func, "__qualname__", repr(func)))


class StepCompiler:
    """Trace an ordered list of accelerated units into one jitted step.

    ``order`` is the execution order of the accelerated cycle body
    (forwards → evaluator → gds), excluding host-side units (loader,
    decision, plotters) — exactly the partition SURVEY.md §7 stage 2
    prescribes.
    """

    def __init__(self, units, device: XLADevice, donate=True):
        self.units = list(units)
        self.device = device
        # donation is the TPU HBM lever; on the CPU platform it buys
        # nothing and jaxlib 0.4.37 was observed to flakily SEGFAULT
        # converting/awaiting outputs of donated programs on the
        # 8-virtual-device test mesh (use-after-free in the donated
        # aliasing path) — so only donate on real accelerators
        self.donate = bool(donate) and \
            getattr(device, "platform", None) != "cpu"
        #: in-graph model-stat collection (veles/model_health.py):
        #: toggled by XLAStep; both are part of every compile-cache
        #: key, since they change the traced program
        self.collect_stats = False
        self.stats_stride = 1
        self._compiled = {}

    # pytree assembly ---------------------------------------------------

    def gather_params(self):
        return {u.name: u.export_params() for u in self.units
                if u.export_params()}

    def gather_state(self):
        return {u.name: u.export_state() for u in self.units
                if u.export_state()}

    def scatter_params(self, params):
        for u in self.units:
            if u.name in params:
                u.import_params(params[u.name])

    def scatter_device_params(self, params):
        """Keep device values resident: mark unit Arrays device-dirty
        without a host round-trip."""
        for u in self.units:
            tree = params.get(u.name)
            if not tree:
                continue
            for attr, value in tree.items():
                arr = getattr(u, attr, None)
                if isinstance(arr, Array):
                    arr.set_device_value(value)

    # compilation -------------------------------------------------------

    def trace_step(self, params, state, hyper, key, train, units, bind):
        """The ONE step-body trace shared by per-step and scan
        compilation: build the context, bind the batch (caller-supplied
        closure), run every unit's ``xla_run``."""
        ctx = FlowContext(self, dict(params), dict(state), hyper,
                          key, train)
        bind(ctx)
        for unit in units:
            if not train and getattr(unit, "train_only", False):
                continue
            unit.xla_run(ctx)
        return ctx

    def build_step(self, batch_spec, train=True):
        """Return ``step(params, state, batch, hyper, key)``.

        ``batch_spec``: dict name -> (unit, attr) describing which unit
        attrs the batch tensors feed (e.g. the loader's minibatch).
        """
        import jax

        units = self.units

        def step(params, state, batch, hyper, key):
            def bind(ctx):
                for name, (unit, attr) in batch_spec.items():
                    ctx.set(unit, attr, batch[name])
            ctx = self.trace_step(params, state, hyper, key, train,
                                  units, bind)
            return ctx.params, ctx.state, ctx.outputs

        donate = (0, 1) if (self.donate and train) else ()
        return jax.jit(step, donate_argnums=donate)

    def compile(self, batch_spec, train=True):
        key = (tuple(sorted((name, unit.name, attr)
                            for name, (unit, attr) in batch_spec.items())),
               train, self.collect_stats, self.stats_stride)
        if key not in self._compiled:
            t0 = time.perf_counter()
            self._compiled[key] = self.build_step(batch_spec, train=train)
            _compile_cache_event("step", False,
                                 time.perf_counter() - t0, t0)
        else:
            _compile_cache_event("step", True)
        return self._compiled[key]

    # class-scan compilation (SURVEY.md §7 design stance, taken one
    # step further: not just one fused step, but a whole class segment
    # of an epoch as ONE lax.scan program — zero per-minibatch dispatch
    # or host sync; the dataset stays device-resident and minibatches
    # are gathered by index on device) -------------------------------

    def build_epoch_scan(self, batch_spec, segments, transform=None):
        """Return ``chunk(params, state, full, idxs, valids, hyper,
        key0, offsets) -> (params, state, {seg: stacked_outputs})``.

        ``transform``: the loader's ``xla_batch_transform`` applied on
        DEVICE to each gathered minibatch (uint8 bank -> cropped
        normalized float etc.); None = identity.

        ``segments``: list of ``(seg_key, train_flag, units)`` — one
        per loader class served each epoch, in serving order. ``full``:
        dict name -> whole-dataset device array; ``idxs[seg_key]``:
        (E, n_mb, mb) int32 row indices for E consecutive epochs;
        ``valids[seg_key]``: (n_mb,) true row counts (identical across
        epochs — class sizes don't change); ``offsets``: (E,) int32
        step index at each epoch's start (seeds the per-step PRNG keys
        exactly as E separate dispatches would).

        Structure: an outer ``lax.scan`` over epochs, an inner
        ``lax.scan`` per class segment whose iterations gather their
        minibatch from ``full`` on device and run the fused step body.
        E epochs become ONE XLA program with a single host round-trip
        for their metrics — the round-trip (~100ms on a remote-tunnel
        TPU) is the dominant per-dispatch cost, so chunking it across
        epochs is the main throughput lever after fusion itself.
        """
        import jax
        import jax.numpy as jnp

        segments = [(k, t, list(us)) for k, t, us in segments]
        spec = dict(batch_spec)
        if transform is None:
            transform = lambda name, t, train=False: t

        def chunk_fn(params, state, full, idxs, valids, hyper, key0,
                     offsets):
            def epoch_body(carry, xs):
                params, state = carry
                offset, idx_epoch = xs
                epoch_key = jax.random.fold_in(key0, offset)
                outs_all = {}
                for seg_i, (seg_key, train, units) in enumerate(segments):
                    seg_base_key = jax.random.fold_in(epoch_key, seg_i)

                    def body(carry, xs, _units=units, _train=train,
                             _key=seg_base_key):
                        params, state = carry
                        i, idx, valid = xs

                        def bind(ctx):
                            for name, (unit, attr) in spec.items():
                                if name == "batch_size":
                                    ctx.set(unit, attr, valid)
                                else:
                                    ctx.set(unit, attr, transform(
                                        name, full[name][idx],
                                        train=_train))
                        ctx = self.trace_step(
                            params, state, hyper,
                            jax.random.fold_in(_key, i), _train, _units,
                            bind)
                        return (ctx.params, ctx.state), ctx.outputs

                    idx_mat = idx_epoch[seg_key]
                    n_mb = idx_mat.shape[0]
                    (params, state), outs = jax.lax.scan(
                        body, (params, state),
                        (jnp.arange(n_mb), idx_mat, valids[seg_key]))
                    outs_all[seg_key] = outs
                return (params, state), outs_all

            (params, state), outs_all = jax.lax.scan(
                epoch_body, (params, state), (offsets, idxs))
            return params, state, outs_all

        donate = (0, 1) if self.donate else ()
        return jax.jit(chunk_fn, donate_argnums=donate)

    def compile_epoch_scan(self, batch_spec, segments, transform=None):
        key = ("epoch",
               tuple(sorted((name, unit.name, attr)
                            for name, (unit, attr) in batch_spec.items())),
               tuple((k, t, tuple(u.name for u in us))
                     for k, t, us in segments),
               _transform_key(transform), self.collect_stats,
               self.stats_stride)
        if key not in self._compiled:
            t0 = time.perf_counter()
            self._compiled[key] = self.build_epoch_scan(
                batch_spec, segments, transform)
            _compile_cache_event("epoch", False,
                                 time.perf_counter() - t0, t0)
        else:
            _compile_cache_event("epoch", True)
        return self._compiled[key]

    # window-scan compilation (the STREAMING fast path: the dataset
    # does not fit on device, so stacked windows of minibatches are
    # shipped up and consumed by one scan program each — one dispatch
    # and one metric fetch per window instead of per minibatch) -------

    def build_window_scan(self, batch_spec, train, units, transform):
        """Return ``window(params, state, stacked, valids, hyper, key0)
        -> (params, state, stacked_outputs)``.

        ``stacked``: dict name -> (B, mb, ...) host-built minibatch
        stack; ``valids``: (B,) true row counts; ``transform``: the
        loader's ``xla_batch_transform`` (device-side uint8→float
        normalization etc.), applied per minibatch inside the scan.
        """
        import jax
        import jax.numpy as jnp

        units = list(units)
        spec = dict(batch_spec)

        def window_fn(params, state, stacked, valids, hyper, key0):
            def body(carry, xs):
                params, state = carry
                i, batch, valid = xs

                def bind(ctx):
                    for name, (unit, attr) in spec.items():
                        if name == "batch_size":
                            ctx.set(unit, attr, valid)
                        elif name in batch:
                            ctx.set(unit, attr,
                                    transform(name, batch[name],
                                              train=train))
                ctx = self.trace_step(
                    params, state, hyper, jax.random.fold_in(key0, i),
                    train, units, bind)
                return (ctx.params, ctx.state), ctx.outputs

            n_mb = valids.shape[0]
            (params, state), outs = jax.lax.scan(
                body, (params, state),
                (jnp.arange(n_mb), stacked, valids))
            return params, state, outs

        donate = (0, 1) if self.donate else ()
        return jax.jit(window_fn, donate_argnums=donate)

    def compile_window_scan(self, batch_spec, train, units, transform):
        key = ("window",
               tuple(sorted((name, unit.name, attr)
                            for name, (unit, attr) in batch_spec.items())),
               train, tuple(u.name for u in units),
               _transform_key(transform), self.collect_stats,
               self.stats_stride)
        if key not in self._compiled:
            t0 = time.perf_counter()
            self._compiled[key] = self.build_window_scan(
                batch_spec, train, units, transform)
            _compile_cache_event("window", False,
                                 time.perf_counter() - t0, t0)
        else:
            _compile_cache_event("window", True)
        return self._compiled[key]


class AcceleratedWorkflow(Workflow):
    """Workflow owning a Device (reference ``AcceleratedWorkflow`` [U])."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.device = None

    def initialize(self, device=None, **kwargs):
        self.device = get_device(device)
        return super().initialize(device=self.device, **kwargs)

    @property
    def on_xla(self):
        return self.device is not None and self.device.is_xla
