"""Pluggable input-data normalizers.

Re-design of ``veles/normalization.py`` [U] (SURVEY.md §2.3
"Normalizers": "pluggable input normalization (linear, mean-dispersion,
pointwise, external-mean...)"). Shape:

* a registry keyed by config name — loaders take
  ``normalization_type="mean_disp"`` +
  ``normalization_parameters={...}`` and build the normalizer via
  :func:`factory`;
* two-phase API: :meth:`analyze` consumes (batches of) TRAINING data
  to fit statistics, :meth:`normalize` applies the fitted transform to
  any array (analyze may be called repeatedly — statistics accumulate
  streamingly, so image pipelines never hold the dataset in RAM);
* :meth:`state` / :meth:`set_state` round-trip the fitted statistics
  through checkpoints.

The fitted transform is affine per feature, so ``mean_rdisp()``
exposes every normalizer to the device path as (mean, 1/disp) arrays —
exactly what the on-device ``MeanDispNormalizer`` unit consumes
(veles/znicz_tpu/ops/mean_disp_normalizer.py).
"""

import numpy

NORMALIZERS = {}


def normalizer(name):
    def deco(cls):
        cls.NAME = name
        NORMALIZERS[name] = cls
        return cls
    return deco


def factory(name, **kwargs):
    """Build a normalizer by config name; ``None``/'none' => no-op."""
    if name is None:
        name = "none"
    try:
        cls = NORMALIZERS[name]
    except KeyError:
        raise KeyError("unknown normalization_type %r (known: %s)"
                       % (name, ", ".join(sorted(NORMALIZERS))))
    return cls(**kwargs)


class NormalizerBase:
    """Streaming-analyze / apply API shared by the family."""

    NAME = None

    def analyze(self, batch):
        """Accumulate statistics from a (N, ...) training batch."""

    def normalize(self, arr):
        """Return the normalized array (float32, same shape)."""
        raise NotImplementedError

    # -- checkpoint round-trip ----------------------------------------

    def state(self):
        # EVERYTHING, including accumulator attributes: a checkpoint
        # between analyze() and the first normalize() must restore the
        # in-flight statistics. Arrays are COPIED — the in-place
        # accumulators must not mutate an already-captured state.
        # __name__ records the registry type so restore can rebuild
        # the right class even into a differently-configured loader.
        out = {k: (v.copy() if isinstance(v, numpy.ndarray) else v)
               for k, v in vars(self).items()}
        out["__name__"] = self.NAME
        return out

    def set_state(self, state):
        for k, v in state.items():
            if k == "__name__":
                continue
            setattr(self, k,
                    v.copy() if isinstance(v, numpy.ndarray) else v)


    # -- device-path export -------------------------------------------

    def mean_rdisp(self, sample_shape):
        """(mean, rdisp) arrays of ``sample_shape`` such that
        normalize(x) == (x - mean) * rdisp — feeds the on-device
        MeanDispNormalizer unit. Subclasses with non-affine transforms
        must override or raise."""
        zero = numpy.zeros(sample_shape, numpy.float32)
        one = numpy.ones(sample_shape, numpy.float32)
        probe0 = self.normalize(zero[None])[0]
        probe1 = self.normalize(one[None])[0]
        rdisp = probe1 - probe0
        return -probe0 / numpy.where(rdisp == 0, 1, rdisp), rdisp


def from_state(state):
    """Rebuild a normalizer purely from its checkpointed state."""
    cls = NORMALIZERS[state["__name__"]]
    n = cls.__new__(cls)
    n.set_state(state)
    return n


@normalizer("none")
class NoneNormalizer(NormalizerBase):
    def normalize(self, arr):
        return numpy.asarray(arr, numpy.float32)


@normalizer("linear")
class LinearNormalizer(NormalizerBase):
    """Affine map of the GLOBAL analyzed [min, max] onto
    [interval[0], interval[1]] (default [-1, 1])."""

    def __init__(self, interval=(-1.0, 1.0)):
        self.interval = tuple(float(v) for v in interval)
        self.vmin = numpy.inf
        self.vmax = -numpy.inf

    def analyze(self, batch):
        self.vmin = min(self.vmin, float(numpy.min(batch)))
        self.vmax = max(self.vmax, float(numpy.max(batch)))

    def normalize(self, arr):
        lo, hi = self.interval
        span = self.vmax - self.vmin
        if not numpy.isfinite(span) or span == 0:
            raise ValueError("analyze() never saw data")
        x = numpy.asarray(arr, numpy.float32)
        return (x - self.vmin) * ((hi - lo) / span) + lo


@normalizer("range_linear")
class RangeLinearNormalizer(LinearNormalizer):
    """Linear with a FIXED source range (no analyze needed) — e.g.
    uint8 images: source_range=(0, 255)."""

    def __init__(self, source_range=(0.0, 255.0), interval=(-1.0, 1.0)):
        super().__init__(interval)
        self.vmin, self.vmax = (float(v) for v in source_range)

    def analyze(self, batch):
        pass


@normalizer("mean_disp")
class MeanDispNormalizer(NormalizerBase):
    """Per-feature (x - mean) / dispersion, dispersion = half the
    analyzed per-feature value range (matching the reference's
    mean-dispersion scheme [U]); features with zero range pass
    through centered."""

    def __init__(self):
        self.mean = None
        self._sum = None
        self._min = None
        self._max = None
        self._count = 0

    def analyze(self, batch):
        b = numpy.asarray(batch, numpy.float32)
        if self._sum is None:
            self._sum = b.sum(axis=0)
            self._min = b.min(axis=0)
            self._max = b.max(axis=0)
        else:
            self._sum += b.sum(axis=0)
            numpy.minimum(self._min, b.min(axis=0), out=self._min)
            numpy.maximum(self._max, b.max(axis=0), out=self._max)
        self._count += len(b)
        # new data invalidates the fitted transform: re-fit lazily so
        # streaming accumulation keeps the documented semantics
        self.mean = None

    def _fit(self):
        if self._count == 0:
            raise ValueError("analyze() never saw data")
        self.mean = (self._sum / self._count).astype(numpy.float32)
        disp = (self._max - self._min).astype(numpy.float32) / 2.0
        self.rdisp = (1.0 / numpy.where(disp == 0, 1.0, disp)) \
            .astype(numpy.float32)
        return self.mean, self.rdisp

    def normalize(self, arr):
        if self.mean is None:
            self._fit()
        return ((numpy.asarray(arr, numpy.float32) - self.mean)
                * self.rdisp)

    def mean_rdisp(self, sample_shape):
        if self.mean is None:
            self._fit()
        return self.mean, self.rdisp


@normalizer("pointwise")
class PointwiseNormalizer(NormalizerBase):
    """Per-feature affine map of the analyzed [min, max] onto [-1, 1]
    (each pixel/feature scaled independently — the reference's
    pointwise scheme [U])."""

    def __init__(self):
        self._min = None
        self._max = None

    def analyze(self, batch):
        b = numpy.asarray(batch, numpy.float32)
        if self._min is None:
            self._min = b.min(axis=0)
            self._max = b.max(axis=0)
        else:
            numpy.minimum(self._min, b.min(axis=0), out=self._min)
            numpy.maximum(self._max, b.max(axis=0), out=self._max)

    def normalize(self, arr):
        if self._min is None:
            raise ValueError("analyze() never saw data")
        span = self._max - self._min
        scale = (2.0 / numpy.where(span == 0, 1.0, span)) \
            .astype(numpy.float32)
        x = numpy.asarray(arr, numpy.float32)
        return numpy.where(span == 0, 0.0,
                           (x - self._min) * scale - 1.0)


@normalizer("external_mean")
class ExternalMeanNormalizer(NormalizerBase):
    """Subtract an externally-supplied mean array (e.g. the ImageNet
    pixel mean shipped with a dataset [U]); optional scale."""

    def __init__(self, mean=None, scale=1.0):
        if mean is None:
            raise ValueError("external_mean needs mean=")
        self.mean = numpy.asarray(mean, numpy.float32)
        self.scale = float(scale)

    def normalize(self, arr):
        return ((numpy.asarray(arr, numpy.float32) - self.mean)
                * self.scale)
