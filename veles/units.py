"""The dataflow-graph unit runtime.

Re-design of ``veles/units.py`` [U] (SURVEY.md §1 L1, §2.1 "Unit graph").
A :class:`Unit` is a node in a workflow DAG with

* **control edges** — ``b.link_from(a)`` means "b becomes ready after a
  finishes"; a unit runs when *all* its open incoming links have fired
  since its last run;
* **gates** — ``gate_block`` (unit neither runs nor propagates) and
  ``gate_skip`` (unit does not run but propagates), both live
  :class:`veles.mutable.Bool` values so host logic (Decision) can flip
  them mid-epoch;
* **data edges** — ``link_attrs`` aliases attributes across units via
  :class:`veles.mutable.LinkableAttribute`.

Execution is single-threaded and deterministic (the reference used a
thread pool; on TPU all device work is inside one jitted step, so host
scheduling parallelism buys nothing and determinism matters more).
Per-unit wall time is accumulated for the profiling report (SURVEY.md
§5.1).
"""

import time
from collections import OrderedDict

from veles import telemetry
from veles.logger import Logger
from veles.mutable import Bool, LinkableAttribute


class Unit(Logger):
    """Base dataflow node."""

    def __init__(self, workflow, name=None, **kwargs):
        self.name = name or type(self).__name__
        self.workflow = None
        self.links_from = OrderedDict()   # src unit -> fired flag
        self.links_to = OrderedDict()     # dst unit -> None
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self._initialized = False
        self.run_calls = 0
        self.run_time = 0.0
        #: per-unit step-time histogram (the registry-backed upgrade
        #: of the bare run_time float; resolved lazily so the unit
        #: name is final and test-scoped registries are honoured)
        self._run_seconds = telemetry.LazyChild(
            lambda: telemetry.histogram(
                "veles_unit_run_seconds",
                "Wall time of one Unit.run call",
                ("unit",)).labels(self.name))
        if workflow is not None:
            workflow.add_unit(self)

    # -- graph wiring -------------------------------------------------

    def link_from(self, *units) -> "Unit":
        """Add control edges ``unit -> self`` for each argument."""
        for unit in units:
            self.links_from[unit] = False
            unit.links_to[self] = None
        return self

    def unlink_from(self, *units) -> "Unit":
        for unit in units:
            self.links_from.pop(unit, None)
            unit.links_to.pop(self, None)
        return self

    def unlink_all(self) -> "Unit":
        for unit in list(self.links_from):
            self.unlink_from(unit)
        for unit in list(self.links_to):
            unit.unlink_from(self)
        return self

    def link_attrs(self, other, *specs, two_way=False) -> "Unit":
        """Alias attributes of ``self`` to attributes of ``other``.

        Each spec is either a name (same on both sides) or a pair
        ``(my_name, other_name)`` — the reference's ``link_attrs``
        convention [U].
        """
        for spec in specs:
            if isinstance(spec, str):
                mine = theirs = spec
            else:
                mine, theirs = spec
            LinkableAttribute.install(self, mine, other, theirs,
                                      two_way=two_way)
        return self

    # -- lifecycle ----------------------------------------------------

    def initialize(self, **kwargs):
        """Resolve shapes / allocate state. Subclasses override; must be
        idempotent (re-initialize happens on snapshot resume)."""
        self._initialized = True

    @property
    def is_initialized(self):
        return self._initialized

    def run(self):
        """One execution of this unit. Subclasses override."""

    def stop(self):
        """Called once when the workflow stops (cleanup hook)."""

    #: If True the unit runs as soon as ANY incoming link fires (the
    #: reference Repeater's open_gate override [U]); default is an AND
    #: barrier over all open incoming links.
    or_gate = False

    # -- scheduler internals ------------------------------------------

    def _ready(self) -> bool:
        if bool(self.gate_block):
            return False
        if not self.links_from:
            return False
        if self.or_gate:
            return any(self.links_from.values())
        return all(self.links_from.values())

    def _clear_inbox(self):
        for src in self.links_from:
            self.links_from[src] = False

    def _execute(self):
        """Run (honouring gate_skip) and return units signalled next."""
        self._clear_inbox()
        if not bool(self.gate_skip):
            start = time.perf_counter()
            self.run()
            dt = time.perf_counter() - start
            self.run_time += dt
            self.run_calls += 1
            self._run_seconds.get().observe(dt)
            if telemetry.tracer.active:
                telemetry.tracer.add_complete(
                    "%s.run" % self.name, start, dt,
                    unit=type(self).__name__)
        out = []
        for dst in self.links_to:
            if bool(dst.gate_block):
                continue
            dst.links_from[self] = True
            out.append(dst)
        return out

    def __repr__(self):
        return "<%s %r>" % (type(self).__name__, self.name)


class TrivialUnit(Unit):
    """A unit with an empty run (start/end points, barriers)."""


class Repeater(TrivialUnit):
    """Cycle re-entry point: fires downstream whenever ANY of its
    predecessors fires (reference ``Repeater`` [U]; SURVEY.md §1 — the
    training loop is a cycle in the DAG, and the repeater is what lets
    both ``start_point`` and the last GD unit feed the loader)."""

    or_gate = True


class Container:
    """Marker mixin for units that contain other units (Workflow)."""
