"""Distribution contract per unit.

Re-design of ``veles/distributable.py`` [U] (SURVEY.md §2.2). In the
reference, master↔slave data exchange is expressed per unit through the
``IDistributable`` hooks and carried over ZeroMQ. In the TPU build the
*hot path* (gradient averaging) is a ``psum`` inside the jitted step
(see ``veles/parallel``), but the hook API survives as a thin layer:

* tests exercise master/slave merge logic without a cluster (SURVEY.md
  §4 "Distributed tests");
* checkpoint/elasticity tooling uses the same hooks to ship state;
* host-side units (Loader index assignment, Decision aggregation) keep
  their reference semantics under multi-process launches.
"""


class IDistributable:
    """Interface (duck-typed): units override any subset."""

    #: True when the unit has state to exchange.
    negotiates_on_connect = False

    def generate_data_for_slave(self, slave=None):
        """Master: produce the payload shipped to ``slave`` before its
        next iteration (e.g. fresh weights, minibatch index ranges)."""
        return None

    def apply_data_from_master(self, data):
        """Slave: ingest the master payload."""

    def generate_data_for_master(self):
        """Slave: produce the update payload (e.g. weight deltas,
        evaluation counters)."""
        return None

    def apply_data_from_slave(self, data, slave=None):
        """Master: merge a slave update (e.g. parameter averaging)."""

    def drop_slave(self, slave=None):
        """Master: a slave died — requeue its in-flight work. May
        return the number of requeued items (the registry sums these
        into the master's robustness counters)."""


class TriviallyDistributable(IDistributable):
    """No-op mixin for units with nothing to exchange [U]."""


class DistributionRegistry:
    """Collects the distributable units of a workflow and runs the
    master/slave exchange round-trips over them (in-process transport;
    the wire transport lives in ``veles/server.py``/``client.py``)."""

    def __init__(self, workflow):
        self.workflow = workflow

    def units(self):
        for unit in self.workflow:
            if isinstance(unit, IDistributable):
                yield unit

    def generate_job(self, slave=None):
        return {unit.name: unit.generate_data_for_slave(slave)
                for unit in self.workflow
                if isinstance(unit, IDistributable)}

    def apply_job(self, job):
        for unit in self.workflow:
            if isinstance(unit, IDistributable) and unit.name in job:
                unit.apply_data_from_master(job[unit.name])

    def generate_update(self):
        return {unit.name: unit.generate_data_for_master()
                for unit in self.workflow
                if isinstance(unit, IDistributable)}

    def apply_update(self, update, slave=None):
        """Merge one slave update; -> how many units consumed data
        (0 means the payload named no unit of this workflow — a
        config-mismatched peer the master should hear about)."""
        merged = 0
        for unit in self.workflow:
            if isinstance(unit, IDistributable) and unit.name in update:
                unit.apply_data_from_slave(update[unit.name], slave)
                merged += 1
        return merged

    def drop_slave(self, slave=None):
        """Requeue a dead slave's in-flight work across all units;
        -> total requeued items (for the fault counters)."""
        requeued = 0
        for unit in self.workflow:
            if isinstance(unit, IDistributable):
                count = unit.drop_slave(slave)
                if isinstance(count, int):
                    requeued += count
        return requeued
