"""Unified telemetry core: metrics registry + span tracer.

One spine for every metric surface in the tree (SURVEY.md §5.5 — the
reference VELES treated observability as a subsystem: web status,
plotter streams, MongoDB-shipped logs). Before this module, three
disconnected ad-hoc surfaces had grown: ``Unit.run_time`` floats,
hand-rolled p50/p99 dicts in ``veles/serving/batcher.py`` and the
fault-counter dict on ``MasterServer``. They now all emit into ONE
process-wide registry of **Counter / Gauge / Histogram** instruments
with label support, scrapeable in Prometheus text format from both
``web_status.py`` and the serving frontend (``GET /metrics``), while
every pre-existing JSON shape stays available as a *view* over the
registry (``/metrics.json``, ``MasterServer.status()``,
``Workflow.print_stats``).

Registry model
--------------

* module-level **active registry** (:func:`get_registry`); tests swap
  in a fresh one per test via :func:`scoped` so telemetry state can
  never leak across tests;
* instruments are *families* created idempotently by name
  (:func:`counter` / :func:`gauge` / :func:`histogram`); a family with
  declared ``labels`` hands out per-label-value children via
  ``.labels(...)``, a label-less family acts as its own child;
* hot paths hold a :class:`LazyChild` — a call-site handle that
  re-resolves its child only when the active registry changes
  (one int compare per observation in the steady state);
* histograms keep Prometheus cumulative buckets AND a bounded
  reservoir of raw observations, so the serving JSON's p50/p99 view
  stays bit-identical to the pre-registry implementation.

Span tracer
-----------

``with telemetry.span("conv.forward", unit=...)`` records wall-time
events when tracing is enabled (``velescli.py --trace-out PATH``) and
costs one attribute check when it is not. :meth:`Tracer.dump` writes
Chrome-trace/Perfetto-loadable JSON (``chrome://tracing`` or
https://ui.perfetto.dev).

Distributed tracing & flight recorder (ISSUE 6)
-----------------------------------------------

* :class:`TraceContext` — W3C-traceparent-style ``(trace_id,
  span_id, parent_id)`` minted per minibatch job / serving request
  and propagated through the master↔slave pickle frames and the
  serving frontend→batcher→engine chain; spans tagged with the ids
  reconstruct one causal timeline across processes.
* the **flight recorder** — a bounded ring that continuously retains
  the newest spans (:attr:`Tracer.flight`, on by default) plus a
  short log of structured operational events
  (:func:`record_event`: job fenced, lease revoked, checkpoint
  written, reconnect). ``GET /debug/trace`` / ``GET /debug/events``
  on web-status and the serving frontend (and ``velescli debug
  URL``) expose the window from a LIVE process — a postmortem view
  that needs no restart with tracing enabled.
* :meth:`Tracer.absorb_remote` merges completed spans a peer shipped
  over the wire (slaves piggyback them on update frames) into this
  process's buffers, wall-clock anchored, so the master's
  ``--trace-out`` dump shows dispatch → wire → slave-compute → merge
  as one timeline with per-process track names.
"""

import bisect
import collections
import json
import os
import secrets
import threading
import time
from contextlib import contextmanager

#: default histogram buckets (seconds) — spans sub-ms unit runs up to
#: multi-second fused XLA dispatches / compilations
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: raw observations kept per histogram child for percentile queries
#: (same window the serving batcher kept before the registry existed)
RESERVOIR_SIZE = 2048


# -- instruments -------------------------------------------------------


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counters only go up (inc %r)" % (n,))
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = None

    def set(self, v):
        with self._lock:
            self._fn = None
            self._value = float(v)

    def set_function(self, fn):
        """Evaluate ``fn()`` at read/scrape time instead of storing a
        value — for gauges that are an AGE or other now-relative
        quantity (e.g. seconds since the last checkpoint), which a
        stored value would freeze at whatever it was when set."""
        with self._lock:
            self._fn = fn

    def inc(self, n=1):
        with self._lock:
            self._fn = None
            self._value += n

    def dec(self, n=1):
        self.inc(-n)

    @property
    def value(self):
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")
        return self._value


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count",
                 "_reservoir")

    def __init__(self, buckets):
        self._lock = threading.Lock()
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0
        # sliding window over the NEWEST observations; deque(maxlen)
        # evicts in O(1) on the hot path
        self._reservoir = collections.deque(maxlen=RESERVOIR_SIZE)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            self._reservoir.append(v)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Value at quantile ``q`` of the reservoir window, using the
        EXACT index convention the serving metrics always used
        (``sorted[min(n-1, int(n*q))]``) so the JSON view over the
        registry is bit-identical to the pre-registry dicts. None when
        nothing has been observed."""
        with self._lock:
            lat = sorted(self._reservoir)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(len(lat) * q))]

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count), ...] ending at +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for ub, c in zip(self.buckets, counts):
            acc += c
            out.append((ub, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


class _Family:
    """One named instrument: metadata + the per-label-value children.

    ``labelnames`` is the declared label schema for the ``.labels()``
    convenience; internally children are keyed by sorted label-item
    tuples, and :meth:`Registry.absorb_counters` may add children with
    EXTRA labels (the master's per-slave aggregation) — legal in the
    exposition format, merely unidiomatic for a client library."""

    def __init__(self, name, kind, help, labelnames, buckets=None):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children = {}

    def _make_child(self):
        if self.kind == "counter":
            return _CounterChild()
        if self.kind == "gauge":
            return _GaugeChild()
        return _HistogramChild(self.buckets)

    def child(self, items=()):
        items = tuple(sorted(items))
        with self._lock:
            c = self._children.get(items)
            if c is None:
                c = self._children[items] = self._make_child()
            return c

    def labels(self, *values, **kv):
        if values and kv:
            raise ValueError("pass label values either positionally "
                             "or by name, not both")
        if kv:
            if set(kv) != set(self.labelnames):
                raise ValueError(
                    "%s expects labels %r, got %r"
                    % (self.name, self.labelnames, tuple(kv)))
            items = tuple((k, str(v)) for k, v in kv.items())
        else:
            if len(values) != len(self.labelnames):
                raise ValueError(
                    "%s expects %d label value(s) %r, got %d"
                    % (self.name, len(self.labelnames),
                       self.labelnames, len(values)))
            items = tuple(zip(self.labelnames,
                              (str(v) for v in values)))
        return self.child(items)

    def children(self):
        with self._lock:
            return sorted(self._children.items())

    def remove_children(self, match_items):
        """Drop every child whose label items contain all of
        ``match_items`` (e.g. ``(("slave", "3"),)`` evicts a departed
        slave's absorbed series); -> how many were removed. The series
        disappears from exposition and ring sampling — the right
        answer for per-peer gauges whose last value would otherwise
        read as current forever."""
        want = set(match_items)
        with self._lock:
            stale = [k for k in self._children if want <= set(k)]
            for k in stale:
                del self._children[k]
        return len(stale)

    # label-less families act as their own child ----------------------

    def _default(self):
        if self.labelnames:
            raise ValueError(
                "%s has labels %r — use .labels(...)"
                % (self.name, self.labelnames))
        return self.child(())

    def inc(self, n=1):
        self._default().inc(n)

    def set(self, v):
        self._default().set(v)

    def set_function(self, fn):
        self._default().set_function(fn)

    def dec(self, n=1):
        self._default().dec(n)

    def observe(self, v):
        self._default().observe(v)

    @property
    def value(self):
        return self._default().value

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum

    def percentile(self, q):
        return self._default().percentile(q)


def _escape_label(value):
    return value.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(items, extra=()):
    pairs = list(items) + list(extra)
    if not pairs:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _escape_label(str(v))) for k, v in pairs)


def _fmt_value(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Registry:
    """Thread-safe family container + Prometheus renderer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}

    def _family(self, name, kind, help, labels, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, kind, help, labels, buckets=buckets)
            elif fam.kind != kind:
                raise ValueError(
                    "instrument %r already registered as %s, not %s"
                    % (name, fam.kind, kind))
            else:
                # adopt a label schema (and help) the first declared
                # use provides: absorb_counters may have registered
                # the family schema-less before the local instrumented
                # path declared it, and .labels() must keep working
                if not fam.labelnames and labels:
                    fam.labelnames = tuple(labels)
                if not fam.help and help:
                    fam.help = help
            return fam

    def counter(self, name, help="", labels=()):
        return self._family(name, "counter", help, labels)

    def gauge(self, name, help="", labels=()):
        return self._family(name, "gauge", help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS):
        return self._family(name, "histogram", help, labels,
                            buckets=tuple(buckets))

    def families(self):
        with self._lock:
            return [self._families[k]
                    for k in sorted(self._families)]

    # -- queries -------------------------------------------------------

    def counter_total(self, name, **match):
        """Sum of a counter family's children whose labels contain
        every ``match`` item; 0.0 when the family does not exist (a
        scrape-side convenience, e.g. bench rows)."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None:
            return 0.0
        want = {(k, str(v)) for k, v in match.items()}
        total = 0.0
        for items, child in fam.children():
            if want <= set(items):
                total += child.value
        return total

    def counter_state(self, exclude_prefixes=(),
                      exclude_label_keys=()):
        """{(name, label_items): value} for every counter child —
        the wire-shippable absolute state a slave diffs against its
        last push (see ``SlaveClient``). ``exclude_label_keys`` skips
        children carrying those labels: a co-located master+slave pair
        shares one registry, and already-absorbed ``slave="N"`` series
        must never be pushed back (they would re-absorb forever)."""
        out = {}
        skip = set(exclude_label_keys)
        for fam in self.families():
            if fam.kind != "counter":
                continue
            if any(fam.name.startswith(p) for p in exclude_prefixes):
                continue
            for items, child in fam.children():
                if skip and any(k in skip for k, _ in items):
                    continue
                out[(fam.name, items)] = child.value
        return out

    def absorb_counters(self, deltas, extra_labels=()):
        """Merge counter deltas pushed by a peer (the master
        aggregating slave counters carried on update messages). Each
        child lands under its original name + labels with
        ``extra_labels`` appended (e.g. ``("slave", "3")``), so one
        scrape shows the whole cluster without colliding with this
        process's own series."""
        extra = tuple(extra_labels)
        for (name, items), v in deltas.items():
            if v <= 0:
                continue
            fam = self.counter(name)
            fam.child(tuple(items) + extra).inc(v)

    # -- exposition ----------------------------------------------------

    def render_prometheus(self):
        """The registry in Prometheus text exposition format 0.0.4."""
        lines = []
        for fam in self.families():
            # HELP escaping per the 0.0.4 format: backslash and
            # newline (label VALUES additionally escape the double
            # quote — see _escape_label)
            lines.append("# HELP %s %s"
                         % (fam.name,
                            (fam.help or fam.name)
                            .replace("\\", "\\\\").replace("\n", "\\n")))
            lines.append("# TYPE %s %s" % (fam.name, fam.kind))
            for items, child in fam.children():
                if fam.kind in ("counter", "gauge"):
                    lines.append("%s%s %s" % (
                        fam.name, _fmt_labels(items),
                        _fmt_value(child.value)))
                    continue
                for ub, acc in child.cumulative_buckets():
                    lines.append("%s_bucket%s %d" % (
                        fam.name,
                        _fmt_labels(items, (("le", _fmt_value(ub)),)),
                        acc))
                lines.append("%s_sum%s %s" % (
                    fam.name, _fmt_labels(items),
                    repr(float(child.sum))))
                lines.append("%s_count%s %d" % (
                    fam.name, _fmt_labels(items), child.count))
        return "\n".join(lines) + "\n"

    #: content type a /metrics endpoint should reply with
    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- active-registry plumbing ------------------------------------------

_swap_lock = threading.Lock()
_active = Registry()
_generation = 0


def get_registry() -> Registry:
    return _active


def set_registry(registry: Registry) -> Registry:
    """Swap the active registry (-> the previous one). Bumps the
    generation so every :class:`LazyChild` re-resolves."""
    global _active, _generation
    with _swap_lock:
        previous = _active
        _active = registry
        _generation += 1
    return previous


def generation() -> int:
    return _generation


@contextmanager
def scoped(registry: Registry = None):
    """``with scoped():`` — run under a fresh (or given) registry,
    restoring the previous one on exit. The per-test isolation hook
    (autouse fixture in ``tests/conftest.py``)."""
    registry = registry if registry is not None else Registry()
    previous = set_registry(registry)
    try:
        yield registry
    finally:
        set_registry(previous)


def counter(name, help="", labels=()):
    return _active.counter(name, help=help, labels=labels)


def gauge(name, help="", labels=()):
    return _active.gauge(name, help=help, labels=labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS):
    return _active.histogram(name, help=help, labels=labels,
                             buckets=buckets)


class LazyChild:
    """A call-site instrument handle for hot paths: ``factory`` is
    invoked on first use and again only when the active registry has
    been swapped (test isolation), so the steady-state cost of
    ``handle.get().observe(dt)`` is one int compare + the child op."""

    __slots__ = ("_factory", "_gen", "_child")

    def __init__(self, factory):
        self._factory = factory
        self._gen = -1
        self._child = None

    def get(self):
        g = _generation
        if g != self._gen:
            self._child = self._factory()
            self._gen = g
        return self._child


# -- trace context -----------------------------------------------------


class TraceContext:
    """W3C-traceparent-style identity of one causal chain.

    ``trace_id`` (32 hex chars) names the whole request/minibatch
    job; ``span_id`` (16 hex chars) names one hop; ``parent_id`` is
    the span this one descends from. Contexts ride the master↔slave
    pickle frames (:meth:`to_wire`) and HTTP ``traceparent`` headers
    (:meth:`to_traceparent`); spans tagged with :meth:`span_args`
    can be stitched back into one cross-process timeline."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def new(cls):
        return cls(secrets.token_hex(16), secrets.token_hex(8))

    def child(self):
        """A new span in the SAME trace, parented on this one."""
        return TraceContext(self.trace_id, secrets.token_hex(8),
                            self.span_id)

    # -- serialization -------------------------------------------------

    def to_wire(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, doc):
        """Rebuild from a frame payload; None on anything malformed —
        a peer speaking an older protocol must not kill the run."""
        if not isinstance(doc, dict):
            return None
        trace_id, span_id = doc.get("trace_id"), doc.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        return cls(trace_id, span_id, doc.get("parent_id"))

    def to_traceparent(self):
        return "00-%s-%s-01" % (self.trace_id, self.span_id)

    @classmethod
    def from_traceparent(cls, header):
        """Parse a ``traceparent`` header; None when malformed."""
        if not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        _, trace_id, span_id, _ = parts
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
        except ValueError:
            return None
        return cls(trace_id, span_id)

    def span_args(self):
        """The ids as span ``args`` (what links events in the dump)."""
        out = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            out["parent_id"] = self.parent_id
        return out


#: thread-local holder of the ACTIVE trace context: the one the code
#: currently executing on this thread works on behalf of. Set with
#: :func:`context`; read by anything that wants to correlate its
#: output with the distributed trace — most importantly the JSONL log
#: handler (``veles/logger.py``), which stamps every structured log
#: line with the active ``trace_id``/``span_id`` so ``/debug/trace``
#: spans and log lines join on one key.
_context_tls = threading.local()


def current_context():
    """The :class:`TraceContext` bound to THIS thread (via
    :func:`context`), or None when the thread is not working on
    behalf of any traced request/job."""
    return getattr(_context_tls, "ctx", None)


@contextmanager
def context(ctx):
    """``with telemetry.context(trace):`` — bind ``ctx`` as the
    thread's active trace context for the duration of the block
    (restoring whatever was active before, so nesting works). Log
    lines emitted inside the block carry the ids (JSONL sink);
    ``ctx`` may be None, which reads as "no active trace"."""
    prev = getattr(_context_tls, "ctx", None)
    _context_tls.ctx = ctx
    try:
        yield ctx
    finally:
        _context_tls.ctx = prev


# -- span tracer -------------------------------------------------------


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_start")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.add_complete(
            self._name, self._start,
            time.perf_counter() - self._start, **self._args)
        return False


def _jsonable(v):
    return v if isinstance(v, (int, float, str, bool, type(None))) \
        else str(v)


class Tracer:
    """Wall-time span recorder dumping Chrome-trace JSON.

    Two recording surfaces share one ``add_complete`` entry point:

    * the **full-run buffer** (``enabled``, off by default) — every
      span since :meth:`start`, dumped by ``--trace-out``;
    * the **flight recorder** (``flight``, ON by default) — a bounded
      ring of the newest spans, readable any time via
      :meth:`flight_doc` (``GET /debug/trace``). Always-on postmortem
      coverage for a live cluster at the cost of one dict build +
      ring append per span.

    Callers guard hot paths with ``if tracer.active`` (one attribute
    read); ``span()`` returns a shared no-op context manager when
    neither surface records."""

    #: full-run event-buffer cap (~200MB of dicts; multi-GB traces
    #: don't load in chrome://tracing anyway). Oldest events are
    #: dropped first — for a crash postmortem the tail is what
    #: matters — and the drop count lands in the dump's otherData AND
    #: the veles_trace_dropped_events_total counter, so a scrape can
    #: see that a trace window is incomplete.
    max_events = 1_000_000
    #: flight-recorder ring cap (newest spans win)
    flight_max_events = 16384
    #: default time window flight_doc() serves
    flight_window = 300.0
    #: structured operational events retained (record_event)
    max_log_events = 1024

    def __init__(self):
        self.enabled = False
        #: continuous bounded-ring recording (the flight recorder);
        #: on by default — this is what makes /debug/trace useful on
        #: a cluster that was never started with tracing
        self.flight = True
        self._lock = threading.Lock()
        self._events = collections.deque()
        self._ring = collections.deque(maxlen=self.flight_max_events)
        self._log = collections.deque(maxlen=self.max_log_events)
        self._dropped = 0
        # ring WRAP is normal operation (bounded window by design),
        # so it is counted separately from full-buffer drops and
        # reported as coverage honesty in flight_doc, not as the
        # scraped incomplete-trace counter
        self._ring_evicted = 0
        # one (perf_counter, wall) anchor pair: every event's ts is
        # perf-based (monotonic), and wall = _wall0 + (perf - _t0)
        # is what lets spans from DIFFERENT processes merge onto one
        # timeline (NTP-level skew applies)
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._proc_names = {}
        self._drop_counter = LazyChild(lambda: counter(
            "veles_trace_dropped_events_total",
            "Span events dropped from the tracer's bounded buffers "
            "(a growing count means trace windows are incomplete)"))

    @property
    def active(self):
        """True when add_complete records ANYTHING (full buffer or
        flight ring) — the one cheap guard for instrumentation sites
        that do extra work to build a span."""
        return self.enabled or self.flight

    def start(self):
        with self._lock:
            self._events = collections.deque()
            self._dropped = 0
            self._t0 = time.perf_counter()
            self._wall0 = time.time()
            self.enabled = True

    def stop(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._events = collections.deque()
            self._ring.clear()
            self._log.clear()
            self._proc_names.clear()
            self._dropped = 0
            self._ring_evicted = 0

    def set_process_name(self, name, pid=None):
        """Name a pid's track in the dumps (Chrome ``process_name``
        metadata). Used for "master" / "slave:N" / "serving" so the
        merged cluster timeline reads as processes, not pids."""
        with self._lock:
            self._proc_names[int(pid if pid is not None
                                 else os.getpid())] = str(name)

    def span(self, name, **args):
        if not (self.enabled or self.flight):
            return _NULL_SPAN
        return _Span(self, name, args)

    def add_complete(self, name, start, duration, **args):
        """Record one complete ('ph: X') event; ``start`` is a
        ``time.perf_counter()`` reading, ``duration`` seconds."""
        if not (self.enabled or self.flight):
            return
        ev = {
            "name": name,
            "ph": "X",
            "ts": (start - self._t0) * 1e6,       # Chrome wants µs
            "dur": duration * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = {k: _jsonable(v) for k, v in args.items()}
        self._record(ev, self._wall0 + (start - self._t0))

    def _record(self, ev, wall):
        dropped = False
        with self._lock:
            if self.enabled:
                if len(self._events) >= self.max_events:
                    self._events.popleft()
                    self._dropped += 1
                    dropped = True
                self._events.append(ev)
            if self.flight:
                if len(self._ring) == self._ring.maxlen:
                    self._ring_evicted += 1
                self._ring.append((wall, ev))
        if dropped:
            # outside the tracer lock: the counter has its own
            self._drop_counter.get().inc()

    def absorb_remote(self, spans, process_name=None):
        """Merge completed spans a peer process shipped over the wire
        (the master absorbing slave spans off update frames). Each
        span dict carries an absolute ``wall`` start (``time.time``
        seconds), ``dur`` seconds, ``name``, ``pid``/``tid`` and
        optional ``args`` (incl. trace-context ids); wall-clock
        anchoring is what lets one merged timeline span processes.
        Malformed entries are skipped — a bad peer must not kill the
        absorbing side."""
        if not (self.enabled or self.flight):
            return 0
        absorbed = 0
        named = set()
        for s in spans:
            try:
                wall = float(s["wall"])
                ev = {"name": str(s["name"]), "ph": "X",
                      "ts": (wall - self._wall0) * 1e6,
                      "dur": float(s["dur"]) * 1e6,
                      "pid": int(s.get("pid", 0)),
                      "tid": int(s.get("tid", 0)) & 0x7FFFFFFF}
            except (KeyError, TypeError, ValueError):
                continue
            args = s.get("args")
            if isinstance(args, dict) and args:
                ev["args"] = {str(k): _jsonable(v)
                              for k, v in args.items()}
            if process_name and ev["pid"] not in named:
                # once per distinct pid, not per span: the name is
                # constant and this runs on the master's update path
                named.add(ev["pid"])
                self.set_process_name(process_name, pid=ev["pid"])
            self._record(ev, wall)
            absorbed += 1
        return absorbed

    # -- structured events (the /debug/events log) ----------------------

    def record_event(self, event, **fields):
        """Append one structured operational event (job fenced, lease
        revoked, checkpoint written, reconnect, ...) to the bounded
        postmortem log. Always on: these are rare by construction.
        ``fields`` may use any names except ``wall``/``event``."""
        ev = {"wall": time.time(), "event": str(event)}
        for k, v in fields.items():
            ev[k] = _jsonable(v)
        with self._lock:
            self._log.append(ev)

    def recent_events(self, limit=None):
        """Newest-last structured events (``GET /debug/events``).
        ``limit`` is clamped defensively: it arrives straight from a
        query string, so 0/negative means none and inf/nan means
        unlimited rather than an exception in the HTTP handler."""
        with self._lock:
            out = list(self._log)
        if limit is None:
            return out
        try:
            n = int(limit)
        except (ValueError, OverflowError):
            return out
        return out[-n:] if n > 0 else []

    # -- reads -----------------------------------------------------------

    def events(self):
        with self._lock:
            return list(self._events)

    def _metadata_events(self):
        # caller holds no lock requirement: _proc_names is snapshotted
        with self._lock:
            names = dict(self._proc_names)
        return [{"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": name}}
                for pid, name in sorted(names.items())]

    def flight_spans(self, window=None):
        """The raw flight-recorder window as ``(wall, event)`` pairs
        (newest-last, event dicts copied) — the feed the critical-path
        analyzer (``veles/profiling.py``) consumes. ``window`` in
        seconds, default :attr:`flight_window`."""
        now = time.time()
        window = self.flight_window if window is None \
            else max(float(window), 0.0)
        cutoff = now - window
        with self._lock:
            return [(w, dict(ev)) for w, ev in self._ring
                    if w >= cutoff]

    def flight_doc(self, window=None):
        """Perfetto/Chrome-trace JSON document of the flight-recorder
        window: the newest spans within ``window`` seconds (default
        :attr:`flight_window`), timestamps re-based to the window
        start. This is what ``GET /debug/trace`` serves — a live,
        bounded postmortem view with zero restart required."""
        now = time.time()
        window = self.flight_window if window is None \
            else max(float(window), 0.0)
        cutoff = now - window
        with self._lock:
            kept = [(w, ev) for w, ev in self._ring if w >= cutoff]
            evicted = self._ring_evicted
        base = min(w for w, _ in kept) if kept else now
        events = []
        for w, ev in kept:
            ev = dict(ev)
            ev["ts"] = (w - base) * 1e6
            events.append(ev)
        return {
            "traceEvents": self._metadata_events() + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "window_s": "%g" % window,
                # coverage honesty: under span pressure the bounded
                # ring holds LESS than the requested window — readers
                # compare covered_s against window_s and see
                # ring_evicted grow instead of trusting a silently
                # truncated view
                "covered_s": "%g" % round(now - base, 3),
                "ring_evicted": str(evicted),
                "base_unix_s": repr(base),
                "spans": str(len(events)),
                "dropped_events": str(self._dropped),
            },
        }

    def dump(self, path):
        """Write the recorded events as Chrome-trace JSON (loadable by
        chrome://tracing and Perfetto); -> ``path``."""
        doc = {"traceEvents": self._metadata_events() + self.events(),
               "displayTimeUnit": "ms"}
        if self._dropped:
            doc["otherData"] = {"dropped_events": str(self._dropped)}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


tracer = Tracer()


def span(name, **args):
    """``with telemetry.span("conv.forward", unit=u):`` — module-level
    convenience over the process tracer."""
    return tracer.span(name, **args)


def record_event(event, **fields):
    """Module-level convenience over :meth:`Tracer.record_event`."""
    tracer.record_event(event, **fields)


def debug_endpoint(path):
    """Route a ``/debug/*`` HTTP path to its payload dict, or None
    when the path is not a debug surface. Shared by ``web_status.py``
    and the serving frontend so both speak the exact same debug
    protocol (and ``velescli debug`` works against either):

    * ``/debug/trace[?window=SECS]`` — Perfetto JSON of the flight-
      recorder window;
    * ``/debug/events[?limit=N]``    — recent structured events;
    * ``/debug/critical_path[?window=SECS]`` — the flight-recorder
      window aggregated into the per-leg "where the step time goes"
      document (``veles/profiling.py``).

    ``/debug/profile`` is deliberately NOT here: its capture blocks
    for the requested window, so both frontends route it through
    ``request.defer`` to ``profiling.profile_endpoint`` instead of an
    inline reply (zlint ``profiler-safety``).
    """
    from urllib.parse import parse_qs, urlparse
    parsed = urlparse(path)
    query = parse_qs(parsed.query)

    def _num(key):
        try:
            return float(query[key][0])
        except (KeyError, IndexError, ValueError):
            return None

    if parsed.path == "/debug/trace":
        return tracer.flight_doc(_num("window"))
    if parsed.path == "/debug/events":
        return {"events": tracer.recent_events(_num("limit"))}
    if parsed.path == "/debug/critical_path":
        from veles import profiling
        return profiling.critical_path_doc(_num("window"))
    return None
