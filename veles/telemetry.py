"""Unified telemetry core: metrics registry + span tracer.

One spine for every metric surface in the tree (SURVEY.md §5.5 — the
reference VELES treated observability as a subsystem: web status,
plotter streams, MongoDB-shipped logs). Before this module, three
disconnected ad-hoc surfaces had grown: ``Unit.run_time`` floats,
hand-rolled p50/p99 dicts in ``veles/serving/batcher.py`` and the
fault-counter dict on ``MasterServer``. They now all emit into ONE
process-wide registry of **Counter / Gauge / Histogram** instruments
with label support, scrapeable in Prometheus text format from both
``web_status.py`` and the serving frontend (``GET /metrics``), while
every pre-existing JSON shape stays available as a *view* over the
registry (``/metrics.json``, ``MasterServer.status()``,
``Workflow.print_stats``).

Registry model
--------------

* module-level **active registry** (:func:`get_registry`); tests swap
  in a fresh one per test via :func:`scoped` so telemetry state can
  never leak across tests;
* instruments are *families* created idempotently by name
  (:func:`counter` / :func:`gauge` / :func:`histogram`); a family with
  declared ``labels`` hands out per-label-value children via
  ``.labels(...)``, a label-less family acts as its own child;
* hot paths hold a :class:`LazyChild` — a call-site handle that
  re-resolves its child only when the active registry changes
  (one int compare per observation in the steady state);
* histograms keep Prometheus cumulative buckets AND a bounded
  reservoir of raw observations, so the serving JSON's p50/p99 view
  stays bit-identical to the pre-registry implementation.

Span tracer
-----------

``with telemetry.span("conv.forward", unit=...)`` records wall-time
events when tracing is enabled (``velescli.py --trace-out PATH``) and
costs one attribute check when it is not. :meth:`Tracer.dump` writes
Chrome-trace/Perfetto-loadable JSON (``chrome://tracing`` or
https://ui.perfetto.dev).
"""

import bisect
import collections
import json
import os
import threading
import time
from contextlib import contextmanager

#: default histogram buckets (seconds) — spans sub-ms unit runs up to
#: multi-second fused XLA dispatches / compilations
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: raw observations kept per histogram child for percentile queries
#: (same window the serving batcher kept before the registry existed)
RESERVOIR_SIZE = 2048


# -- instruments -------------------------------------------------------


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counters only go up (inc %r)" % (n,))
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = None

    def set(self, v):
        with self._lock:
            self._fn = None
            self._value = float(v)

    def set_function(self, fn):
        """Evaluate ``fn()`` at read/scrape time instead of storing a
        value — for gauges that are an AGE or other now-relative
        quantity (e.g. seconds since the last checkpoint), which a
        stored value would freeze at whatever it was when set."""
        with self._lock:
            self._fn = fn

    def inc(self, n=1):
        with self._lock:
            self._fn = None
            self._value += n

    def dec(self, n=1):
        self.inc(-n)

    @property
    def value(self):
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")
        return self._value


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count",
                 "_reservoir")

    def __init__(self, buckets):
        self._lock = threading.Lock()
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0
        # sliding window over the NEWEST observations; deque(maxlen)
        # evicts in O(1) on the hot path
        self._reservoir = collections.deque(maxlen=RESERVOIR_SIZE)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            self._reservoir.append(v)

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Value at quantile ``q`` of the reservoir window, using the
        EXACT index convention the serving metrics always used
        (``sorted[min(n-1, int(n*q))]``) so the JSON view over the
        registry is bit-identical to the pre-registry dicts. None when
        nothing has been observed."""
        with self._lock:
            lat = sorted(self._reservoir)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(len(lat) * q))]

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count), ...] ending at +Inf."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for ub, c in zip(self.buckets, counts):
            acc += c
            out.append((ub, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


class _Family:
    """One named instrument: metadata + the per-label-value children.

    ``labelnames`` is the declared label schema for the ``.labels()``
    convenience; internally children are keyed by sorted label-item
    tuples, and :meth:`Registry.absorb_counters` may add children with
    EXTRA labels (the master's per-slave aggregation) — legal in the
    exposition format, merely unidiomatic for a client library."""

    def __init__(self, name, kind, help, labelnames, buckets=None):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children = {}

    def _make_child(self):
        if self.kind == "counter":
            return _CounterChild()
        if self.kind == "gauge":
            return _GaugeChild()
        return _HistogramChild(self.buckets)

    def child(self, items=()):
        items = tuple(sorted(items))
        with self._lock:
            c = self._children.get(items)
            if c is None:
                c = self._children[items] = self._make_child()
            return c

    def labels(self, *values, **kv):
        if values and kv:
            raise ValueError("pass label values either positionally "
                             "or by name, not both")
        if kv:
            if set(kv) != set(self.labelnames):
                raise ValueError(
                    "%s expects labels %r, got %r"
                    % (self.name, self.labelnames, tuple(kv)))
            items = tuple((k, str(v)) for k, v in kv.items())
        else:
            if len(values) != len(self.labelnames):
                raise ValueError(
                    "%s expects %d label value(s) %r, got %d"
                    % (self.name, len(self.labelnames),
                       self.labelnames, len(values)))
            items = tuple(zip(self.labelnames,
                              (str(v) for v in values)))
        return self.child(items)

    def children(self):
        with self._lock:
            return sorted(self._children.items())

    # label-less families act as their own child ----------------------

    def _default(self):
        if self.labelnames:
            raise ValueError(
                "%s has labels %r — use .labels(...)"
                % (self.name, self.labelnames))
        return self.child(())

    def inc(self, n=1):
        self._default().inc(n)

    def set(self, v):
        self._default().set(v)

    def set_function(self, fn):
        self._default().set_function(fn)

    def dec(self, n=1):
        self._default().dec(n)

    def observe(self, v):
        self._default().observe(v)

    @property
    def value(self):
        return self._default().value

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum

    def percentile(self, q):
        return self._default().percentile(q)


def _escape_label(value):
    return value.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(items, extra=()):
    pairs = list(items) + list(extra)
    if not pairs:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _escape_label(str(v))) for k, v in pairs)


def _fmt_value(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Registry:
    """Thread-safe family container + Prometheus renderer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}

    def _family(self, name, kind, help, labels, buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, kind, help, labels, buckets=buckets)
            elif fam.kind != kind:
                raise ValueError(
                    "instrument %r already registered as %s, not %s"
                    % (name, fam.kind, kind))
            else:
                # adopt a label schema (and help) the first declared
                # use provides: absorb_counters may have registered
                # the family schema-less before the local instrumented
                # path declared it, and .labels() must keep working
                if not fam.labelnames and labels:
                    fam.labelnames = tuple(labels)
                if not fam.help and help:
                    fam.help = help
            return fam

    def counter(self, name, help="", labels=()):
        return self._family(name, "counter", help, labels)

    def gauge(self, name, help="", labels=()):
        return self._family(name, "gauge", help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS):
        return self._family(name, "histogram", help, labels,
                            buckets=tuple(buckets))

    def families(self):
        with self._lock:
            return [self._families[k]
                    for k in sorted(self._families)]

    # -- queries -------------------------------------------------------

    def counter_total(self, name, **match):
        """Sum of a counter family's children whose labels contain
        every ``match`` item; 0.0 when the family does not exist (a
        scrape-side convenience, e.g. bench rows)."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None:
            return 0.0
        want = {(k, str(v)) for k, v in match.items()}
        total = 0.0
        for items, child in fam.children():
            if want <= set(items):
                total += child.value
        return total

    def counter_state(self, exclude_prefixes=(),
                      exclude_label_keys=()):
        """{(name, label_items): value} for every counter child —
        the wire-shippable absolute state a slave diffs against its
        last push (see ``SlaveClient``). ``exclude_label_keys`` skips
        children carrying those labels: a co-located master+slave pair
        shares one registry, and already-absorbed ``slave="N"`` series
        must never be pushed back (they would re-absorb forever)."""
        out = {}
        skip = set(exclude_label_keys)
        for fam in self.families():
            if fam.kind != "counter":
                continue
            if any(fam.name.startswith(p) for p in exclude_prefixes):
                continue
            for items, child in fam.children():
                if skip and any(k in skip for k, _ in items):
                    continue
                out[(fam.name, items)] = child.value
        return out

    def absorb_counters(self, deltas, extra_labels=()):
        """Merge counter deltas pushed by a peer (the master
        aggregating slave counters carried on update messages). Each
        child lands under its original name + labels with
        ``extra_labels`` appended (e.g. ``("slave", "3")``), so one
        scrape shows the whole cluster without colliding with this
        process's own series."""
        extra = tuple(extra_labels)
        for (name, items), v in deltas.items():
            if v <= 0:
                continue
            fam = self.counter(name)
            fam.child(tuple(items) + extra).inc(v)

    # -- exposition ----------------------------------------------------

    def render_prometheus(self):
        """The registry in Prometheus text exposition format 0.0.4."""
        lines = []
        for fam in self.families():
            lines.append("# HELP %s %s"
                         % (fam.name,
                            (fam.help or fam.name).replace("\n", " ")))
            lines.append("# TYPE %s %s" % (fam.name, fam.kind))
            for items, child in fam.children():
                if fam.kind in ("counter", "gauge"):
                    lines.append("%s%s %s" % (
                        fam.name, _fmt_labels(items),
                        _fmt_value(child.value)))
                    continue
                for ub, acc in child.cumulative_buckets():
                    lines.append("%s_bucket%s %d" % (
                        fam.name,
                        _fmt_labels(items, (("le", _fmt_value(ub)),)),
                        acc))
                lines.append("%s_sum%s %s" % (
                    fam.name, _fmt_labels(items),
                    repr(float(child.sum))))
                lines.append("%s_count%s %d" % (
                    fam.name, _fmt_labels(items), child.count))
        return "\n".join(lines) + "\n"

    #: content type a /metrics endpoint should reply with
    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- active-registry plumbing ------------------------------------------

_swap_lock = threading.Lock()
_active = Registry()
_generation = 0


def get_registry() -> Registry:
    return _active


def set_registry(registry: Registry) -> Registry:
    """Swap the active registry (-> the previous one). Bumps the
    generation so every :class:`LazyChild` re-resolves."""
    global _active, _generation
    with _swap_lock:
        previous = _active
        _active = registry
        _generation += 1
    return previous


def generation() -> int:
    return _generation


@contextmanager
def scoped(registry: Registry = None):
    """``with scoped():`` — run under a fresh (or given) registry,
    restoring the previous one on exit. The per-test isolation hook
    (autouse fixture in ``tests/conftest.py``)."""
    registry = registry if registry is not None else Registry()
    previous = set_registry(registry)
    try:
        yield registry
    finally:
        set_registry(previous)


def counter(name, help="", labels=()):
    return _active.counter(name, help=help, labels=labels)


def gauge(name, help="", labels=()):
    return _active.gauge(name, help=help, labels=labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS):
    return _active.histogram(name, help=help, labels=labels,
                             buckets=buckets)


class LazyChild:
    """A call-site instrument handle for hot paths: ``factory`` is
    invoked on first use and again only when the active registry has
    been swapped (test isolation), so the steady-state cost of
    ``handle.get().observe(dt)`` is one int compare + the child op."""

    __slots__ = ("_factory", "_gen", "_child")

    def __init__(self, factory):
        self._factory = factory
        self._gen = -1
        self._child = None

    def get(self):
        g = _generation
        if g != self._gen:
            self._child = self._factory()
            self._gen = g
        return self._child


# -- span tracer -------------------------------------------------------


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_start")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.add_complete(
            self._name, self._start,
            time.perf_counter() - self._start, **self._args)
        return False


def _jsonable(v):
    return v if isinstance(v, (int, float, str, bool, type(None))) \
        else str(v)


class Tracer:
    """Wall-time span recorder dumping Chrome-trace JSON.

    Disabled by default: ``span()`` then returns a shared no-op
    context manager and ``add_complete`` is guarded by callers with
    ``if tracer.enabled`` (one attribute check on the hot path)."""

    #: event-buffer cap (~200MB of dicts; multi-GB traces don't load
    #: in chrome://tracing anyway). Oldest events are dropped first —
    #: for a crash postmortem the tail is what matters — and the drop
    #: count is recorded in the dump's otherData.
    max_events = 1_000_000

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._events = collections.deque()
        self._dropped = 0
        self._t0 = 0.0

    def start(self):
        with self._lock:
            self._events = collections.deque()
            self._dropped = 0
            self._t0 = time.perf_counter()
            self.enabled = True

    def stop(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._events = collections.deque()
            self._dropped = 0

    def span(self, name, **args):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def add_complete(self, name, start, duration, **args):
        """Record one complete ('ph: X') event; ``start`` is a
        ``time.perf_counter()`` reading, ``duration`` seconds."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "ph": "X",
            "ts": (start - self._t0) * 1e6,       # Chrome wants µs
            "dur": duration * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if args:
            ev["args"] = {k: _jsonable(v) for k, v in args.items()}
        with self._lock:
            if len(self._events) >= self.max_events:
                self._events.popleft()
                self._dropped += 1
            self._events.append(ev)

    def events(self):
        with self._lock:
            return list(self._events)

    def dump(self, path):
        """Write the recorded events as Chrome-trace JSON (loadable by
        chrome://tracing and Perfetto); -> ``path``."""
        doc = {"traceEvents": self.events(),
               "displayTimeUnit": "ms"}
        if self._dropped:
            doc["otherData"] = {"dropped_events": str(self._dropped)}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


tracer = Tracer()


def span(name, **args):
    """``with telemetry.span("conv.forward", unit=u):`` — module-level
    convenience over the process tracer."""
    return tracer.span(name, **args)
