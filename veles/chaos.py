"""Deterministic fault injection for the master↔slave wire layer.

:class:`ChaosProxy` is a TCP proxy that sits between a
:class:`~veles.client.SlaveClient` and a
:class:`~veles.server.MasterServer` and mutates traffic at FRAME
granularity (the 4-byte length + 32-byte HMAC + pickle framing from
``veles/server.py``), so tests can prove the fault-tolerance story —
drop→requeue, duplicate-update fencing, reconnect-with-backoff — end
to end over real sockets without ever being flaky themselves:

* every decision comes from either an explicit ``plan`` callable
  (exact frames: "duplicate the 2nd update on connection 0") or a
  per-(connection, direction) PRNG seeded from ``seed`` — never from
  wall-clock or thread scheduling;
* actions: ``pass``, ``drop`` (swallow the frame), ``dup`` (forward
  it twice), ``delay`` (sleep ``delay_s`` first), ``truncate`` (send
  a partial frame, then sever the connection — the mid-frame host
  death);
* :meth:`kill_all` severs every live connection (whole-process slave
  kill); :meth:`stats` counts what was done to whom.

The proxy peeks inside frames (they're this repo's own pickles, on
loopback, in tests) to expose the request kind (``hello`` / ``job`` /
``update`` / ...) to the plan — fencing tests target "the update
frame", not "frame #7".
"""

import random
import socket
import struct
import threading
import time

import numpy

from veles.logger import Logger
from veles.server import _recv_exact, decode_frame_payload


# -- checkpoint/blob corruption (the disk-side fault models) -----------


def poison_update(update, mode="nan", layer=None, key=None):
    """The model-divergence fault (ISSUE 15): poison ONE delta array
    of a generated update payload IN PLACE — the first float array of
    the first (sorted) unit section, or the named ``layer``/``key`` —
    by writing NaN/inf into its element 0. What a blown-up or
    bit-flipped slave ships upstream; the master's wire non-finite
    scan (``apply_data_from_slave`` →
    ``model_health.note_wire_nonfinite``) must catch it, fire the
    divergence SLO and trigger the rollback actuator.

    -> ``(unit_name, entry_key)`` of what was poisoned. Raises
    ValueError when the payload holds no poisonable float array (a
    test asking to poison an eval-only update must fail loudly, not
    silently pass a clean payload through)."""
    bad = float("nan") if mode == "nan" else float("inf")
    for uname in sorted(update):
        if layer is not None and uname != layer:
            continue
        payload = update[uname]
        if not isinstance(payload, dict):
            continue
        for entry in sorted(payload):
            if key is not None and entry != key:
                continue
            value = payload[entry]
            if isinstance(value, numpy.ndarray) \
                    and value.dtype.kind == "f" and value.size:
                # .flat writes through ANY memory layout; a
                # reshape(-1) assignment would land in a silent COPY
                # for non-contiguous arrays and the injection would
                # claim success against a clean payload
                value.flat[0] = bad
                return uname, entry
    raise ValueError(
        "no poisonable float delta in update payload (units: %s)"
        % sorted(update))


def truncate_blob(blob, frac=0.5):
    """The mid-write host death: keep the leading ``frac`` of the
    bytes (at least 1). A gzip/npz cut anywhere in the middle must
    read back as :class:`~veles.snapshotter.CorruptCheckpointError`,
    never as a shorter-but-plausible checkpoint."""
    return bytes(blob[:max(1, int(len(blob) * frac))])


def flip_bit(blob, index=None, bit=0, seed=0):
    """The bit-rot fault: flip ONE bit, deterministically (seeded
    offset by default, exact ``index`` when given), so manifest
    verification — not compression luck — is what catches it."""
    data = bytearray(blob)
    if index is None:
        # stay away from the very start: corrupting the magic bytes
        # tests the container parser, not the sha256 manifest
        index = random.Random(seed).randrange(len(data) // 4,
                                              len(data))
    data[index] ^= 1 << (bit & 7)
    return bytes(data)


def corrupt_store_entry(store, name, mode="truncate", **kwargs):
    """Damage a stored checkpoint IN PLACE through the store's own
    put/get (works for any SnapshotStore backend): ``mode`` is
    ``truncate`` or ``bitflip``."""
    raw = store.get(name)
    if mode == "truncate":
        damaged = truncate_blob(raw, **kwargs)
    elif mode == "bitflip":
        damaged = flip_bit(raw, **kwargs)
    else:
        raise ValueError("mode must be truncate|bitflip, not %r"
                         % (mode,))
    store.put(name, damaged)
    return damaged

PASS = "pass"
DROP = "drop"
DUP = "dup"
DELAY = "delay"
TRUNCATE = "truncate"

ACTIONS = (PASS, DROP, DUP, DELAY, TRUNCATE)

#: client→server / server→client direction tags handed to plans
C2S = "c2s"
S2C = "s2c"


class ChaosEvent:
    """What the plan sees for one frame."""

    __slots__ = ("direction", "conn_id", "index", "kind", "nth")

    def __init__(self, direction, conn_id, index, kind, nth):
        self.direction = direction   # C2S | S2C
        self.conn_id = conn_id       # accept order, 0-based
        self.index = index           # frame number in this direction
        self.kind = kind             # request/response tuple tag
        self.nth = nth               # occurrence number of this kind

    def __repr__(self):
        return ("ChaosEvent(%s conn=%d #%d kind=%r nth=%d)"
                % (self.direction, self.conn_id, self.index,
                   self.kind, self.nth))


class _Pump(threading.Thread):
    """One direction of one proxied connection."""

    def __init__(self, proxy, src, dst, direction, conn_id):
        super().__init__(daemon=True,
                         name="chaos-%s-%d" % (direction, conn_id))
        self.proxy = proxy
        self.src = src
        self.dst = dst
        self.direction = direction
        self.conn_id = conn_id
        # schedule determinism: the rng depends only on (seed,
        # conn_id, direction), never on which pump thread ran first
        self.rng = random.Random(
            (proxy.seed, conn_id, direction).__repr__())
        self.index = 0
        self.kind_counts = {}

    def run(self):
        try:
            while not self.proxy._closing.is_set():
                header = _recv_exact(self.src, 4)
                if header is None:
                    break
                size, = struct.unpack(">I", header)
                tag = _recv_exact(self.src, 32)
                blob = _recv_exact(self.src, size) \
                    if tag is not None else None
                if blob is None:
                    break
                if not self._relay(header, tag, blob):
                    break
        except OSError:
            pass
        finally:
            self.proxy._sever(self.conn_id)

    def _relay(self, header, tag, blob):
        kind = self._peek(blob)
        nth = self.kind_counts[kind] = self.kind_counts.get(kind, 0) + 1
        event = ChaosEvent(self.direction, self.conn_id, self.index,
                           kind, nth)
        self.index += 1
        action = self.proxy._decide(event, self.rng)
        self.proxy._count(self.direction, action)
        frame = header + tag + blob
        if action == DROP:
            self.proxy.debug("drop %r", event)
            return True
        if action == TRUNCATE:
            self.proxy.debug("truncate %r", event)
            try:
                self.dst.sendall(frame[:max(5, len(frame) // 2)])
            except OSError:
                pass
            return False               # sever the connection
        if action == DELAY:
            time.sleep(self.proxy.delay_s)
        try:
            self.dst.sendall(frame)
            if action == DUP:
                self.proxy.debug("dup %r", event)
                self.dst.sendall(frame)
        except OSError:
            return False
        return True

    def _peek(self, blob):
        # frames are our own HMAC-verified-shape payloads on loopback
        # (bare pickle OR the out-of-band buffer format — the shared
        # decoder handles both); surface the protocol tag so plans can
        # target by meaning
        try:
            obj = decode_frame_payload(blob)
            return obj[0] if isinstance(obj, tuple) and obj else None
        except Exception:
            return None


class ChaosProxy(Logger):
    """``ChaosProxy(("127.0.0.1", master_port), seed=7, drop_rate=.02)``
    then point slaves at ``"127.0.0.1:%d" % proxy.port``.

    ``plan(event) -> action|None`` wins when it returns an action;
    ``None`` falls through to the seeded rates (cumulative
    drop/dup/delay/truncate probabilities per frame)."""

    def __init__(self, target, seed=0, plan=None, drop_rate=0.0,
                 dup_rate=0.0, delay_rate=0.0, delay_s=0.05,
                 truncate_rate=0.0, listen_host="127.0.0.1"):
        self.name = "ChaosProxy"
        host, _, port = str(target).rpartition(":") \
            if isinstance(target, str) else (target[0], ":", target[1])
        self.target = (host or "127.0.0.1", int(port))
        self.seed = seed
        self.plan = plan
        self.drop_rate = float(drop_rate)
        self.dup_rate = float(dup_rate)
        self.delay_rate = float(delay_rate)
        self.delay_s = float(delay_s)
        self.truncate_rate = float(truncate_rate)
        self._lock = threading.Lock()
        self._stats = {C2S: dict.fromkeys(ACTIONS, 0),
                       S2C: dict.fromkeys(ACTIONS, 0)}
        self._conns = {}              # conn_id -> (client, upstream)
        self._next_conn = 0
        self._closing = threading.Event()
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen()
        self.port = self._listener.getsockname()[1]
        self.address = "%s:%d" % (listen_host, self.port)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="chaos-accept")
        self._accept_thread.start()

    # -- wiring --------------------------------------------------------

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                break
            try:
                upstream = socket.create_connection(self.target,
                                                    timeout=10)
            except OSError as exc:
                self.warning("upstream %s unreachable: %s",
                             self.target, exc)
                client.close()
                continue
            with self._lock:
                conn_id = self._next_conn
                self._next_conn += 1
                self._conns[conn_id] = (client, upstream)
            _Pump(self, client, upstream, C2S, conn_id).start()
            _Pump(self, upstream, client, S2C, conn_id).start()

    def _sever(self, conn_id):
        with self._lock:
            pair = self._conns.pop(conn_id, None)
        if pair:
            for sock in pair:
                try:
                    sock.close()
                except OSError:
                    pass

    # -- chaos ---------------------------------------------------------

    def _decide(self, event, rng):
        if self.plan is not None:
            action = self.plan(event)
            if action is not None:
                if action not in ACTIONS:
                    raise ValueError("plan returned %r (want one of "
                                     "%s)" % (action, ACTIONS))
                return action
        r = rng.random()
        for rate, action in ((self.drop_rate, DROP),
                             (self.dup_rate, DUP),
                             (self.delay_rate, DELAY),
                             (self.truncate_rate, TRUNCATE)):
            if r < rate:
                return action
            r -= rate
        return PASS

    def _count(self, direction, action):
        with self._lock:
            self._stats[direction][action] += 1

    # -- control / inspection ------------------------------------------

    def kill_all(self):
        """Sever every live connection NOW (abrupt whole-slave death:
        both peers see a reset mid-conversation, nobody sees a FIN
        handshake's politeness)."""
        with self._lock:
            conn_ids = list(self._conns)
        for conn_id in conn_ids:
            self._sever(conn_id)
        return len(conn_ids)

    def stats(self):
        with self._lock:
            return {"connections": self._next_conn,
                    "live": len(self._conns),
                    C2S: dict(self._stats[C2S]),
                    S2C: dict(self._stats[S2C])}

    def faults_injected(self):
        s = self.stats()
        return sum(s[d][a] for d in (C2S, S2C)
                   for a in (DROP, DUP, DELAY, TRUNCATE))

    def close(self):
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.kill_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- HTTP-aware brownouts (ISSUE 13) -----------------------------------


class _Pipe(threading.Thread):
    """One direction of one BrownoutProxy connection: copy bytes,
    applying whatever degradation the proxy currently orders."""

    def __init__(self, proxy, src, dst, direction, conn_id):
        super().__init__(daemon=True,
                         name="brownout-%s-%d" % (direction, conn_id))
        self.proxy = proxy
        self.src = src
        self.dst = dst
        self.direction = direction
        self.conn_id = conn_id

    def run(self):
        try:
            while not self.proxy._closing.is_set():
                data = self.src.recv(65536)
                if not data:
                    break
                delay = self.proxy.latency_s
                if delay > 0:
                    time.sleep(delay)
                if self.proxy.black_hole:
                    self.proxy._count_pipe(self.direction, len(data),
                                           swallowed=True)
                    continue
                self.dst.sendall(data)
                self.proxy._count_pipe(self.direction, len(data))
        except OSError:
            pass
        finally:
            self.proxy._sever(self.conn_id)


class BrownoutProxy(Logger):
    """Byte-level TCP degradation proxy for the HTTP planes.

    :class:`ChaosProxy` speaks the framed master↔slave wire protocol;
    this sibling is FRAME-AGNOSTIC — it forwards raw bytes, so it can
    sit in front of a serving replica's (or router's) HTTP port and
    brown it out deterministically:

    * :meth:`brownout` — inject ``latency_s`` seconds before every
      forwarded read (both directions): probes and proxied requests
      through this target slow to a crawl, exactly the
      sick-but-not-dead replica a router must eject on scrape
      timeout rather than wait out;
    * :meth:`set_black_hole` — swallow bytes entirely (connections
      stay open, nothing ever answers — the wedged-process model);
    * :meth:`restore` — back to a transparent pipe;
    * :meth:`kill_all` — sever every live connection now.

    All knobs are plain attribute flips read by the pump threads per
    chunk, so a test can flip a healthy fleet into brownout (and
    back) mid-scenario without touching the replica itself."""

    def __init__(self, target, listen_host="127.0.0.1"):
        self.name = "BrownoutProxy"
        if isinstance(target, str):
            # accept URL form too ('http://host:port' — the shape
            # router/fleet targets and this proxy's own .url use)
            target = target.split("://", 1)[-1].rstrip("/")
            host, _, port = target.rpartition(":")
        else:
            host, port = target[0], target[1]
        self.target = (host or "127.0.0.1", int(port))
        #: per-chunk forwarding delay (seconds); pump threads read it
        self.latency_s = 0.0
        #: True -> swallow all bytes (connections wedge silently)
        self.black_hole = False
        self._lock = threading.Lock()
        self._stats = {C2S: {"bytes": 0, "swallowed": 0},
                       S2C: {"bytes": 0, "swallowed": 0}}
        self._conns = {}
        self._next_conn = 0
        self._closing = threading.Event()
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen()
        self.port = self._listener.getsockname()[1]
        self.address = "%s:%d" % (listen_host, self.port)
        self.url = "http://%s" % self.address
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="brownout-accept")
        self._accept_thread.start()

    # -- control -------------------------------------------------------

    def brownout(self, latency_s):
        """Inject ``latency_s`` seconds per forwarded chunk."""
        self.latency_s = float(latency_s)
        return self

    def set_black_hole(self, on=True):
        """Swallow (True) or forward (False) all traffic."""
        self.black_hole = bool(on)
        return self

    def restore(self):
        """Back to a transparent pipe (latency 0, forwarding on)."""
        self.latency_s = 0.0
        self.black_hole = False
        return self

    # -- wiring --------------------------------------------------------

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                break
            try:
                upstream = socket.create_connection(self.target,
                                                    timeout=10)
            except OSError as exc:
                self.warning("upstream %s unreachable: %s",
                             self.target, exc)
                client.close()
                continue
            # the connect timeout must not become a recv timeout: a
            # black-holed connection has to WEDGE indefinitely (the
            # documented model), not sever itself after 10s
            upstream.settimeout(None)
            with self._lock:
                conn_id = self._next_conn
                self._next_conn += 1
                self._conns[conn_id] = (client, upstream)
            _Pipe(self, client, upstream, C2S, conn_id).start()
            _Pipe(self, upstream, client, S2C, conn_id).start()

    def _sever(self, conn_id):
        with self._lock:
            pair = self._conns.pop(conn_id, None)
        if pair:
            for sock in pair:
                try:
                    sock.close()
                except OSError:
                    pass

    def _count_pipe(self, direction, n, swallowed=False):
        with self._lock:
            stats = self._stats[direction]
            stats["swallowed" if swallowed else "bytes"] += n

    # -- control / inspection ------------------------------------------

    def kill_all(self):
        """Sever every live proxied connection now."""
        with self._lock:
            conn_ids = list(self._conns)
        for conn_id in conn_ids:
            self._sever(conn_id)
        return len(conn_ids)

    def stats(self):
        with self._lock:
            return {"connections": self._next_conn,
                    "live": len(self._conns),
                    C2S: dict(self._stats[C2S]),
                    S2C: dict(self._stats[S2C])}

    def close(self):
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.kill_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
