"""Inference-archive export for the C++ engine (``libveles/``).

Rebuild of the reference's workflow export consumed by libVeles
(SURVEY.md §3.5: "workflow.export(path) → archive: contents.json +
*.npy"; §2.6 libVeles "loads a workflow archive exported from Python").
The archive is a plain directory:

    contents.json      — format/version, workflow name, ordered unit
                         list with per-unit config + weight file refs
    <unit>_weights.npy — float32 parameter arrays (C-order)

Unit ``type`` strings are the ``forward_unit`` registry names, which
the C++ ``UnitFactory`` registers 1:1 (libveles/src/units.cc), so the
two sides can never drift silently: an unknown type fails loudly in
either direction.
"""

import json
import os

import numpy

#: exactly the types libveles/src/units.cc registers — the export-time
#: contract check; extending the engine means extending BOTH lists
ENGINE_TYPES = frozenset({
    "all2all", "all2all_tanh", "all2all_relu", "all2all_str",
    "all2all_sigmoid", "softmax",
    "conv", "conv_tanh", "conv_relu", "conv_str", "conv_sigmoid",
    "max_pooling", "avg_pooling", "norm", "dropout",
    "activation_tanh", "activation_relu", "activation_str",
    "activation_sigmoid",
    "embedding", "layernorm", "token_dense", "token_dense_relu",
    "transformer_ffn", "attention", "moe_ffn", "transformer_stack",
    "deconv", "depooling",
})


def _npy_name(unit, param):
    return "%s_%s.npy" % (unit.name.replace("/", "_"), param)


def _export_weighted(unit, path, spec):
    w = numpy.ascontiguousarray(unit.weights.map_read().mem,
                                numpy.float32)
    fname = _npy_name(unit, "weights")
    numpy.save(os.path.join(path, fname), w)
    spec["weights"] = fname
    if unit.include_bias and unit.bias:
        b = numpy.ascontiguousarray(unit.bias.map_read().mem,
                                    numpy.float32)
        fname = _npy_name(unit, "bias")
        numpy.save(os.path.join(path, fname), b)
        spec["bias"] = fname
    else:
        spec["bias"] = None


def _save_extra(unit, path, spec, attr, required=True):
    """Export a non-standard parameter Array as its own .npy."""
    arr = getattr(unit, attr, None)
    if arr is None or not arr:
        if required:
            raise ValueError("%s: missing %s" % (unit.name, attr))
        spec[attr] = None
        return
    fname = _npy_name(unit, attr)
    numpy.save(os.path.join(path, fname),
               numpy.ascontiguousarray(arr.map_read().mem,
                                       numpy.float32))
    spec[attr] = fname


def _unit_spec(unit, path):
    """Serialize one forward unit; raises on unsupported types."""
    from veles.znicz_tpu.ops.all2all import All2AllBase
    from veles.znicz_tpu.ops.attention import (
        MultiHeadAttention, TokenDenseBase, TransformerFFN)
    from veles.znicz_tpu.ops.moe import MoEFFN
    from veles.znicz_tpu.ops.transformer_stack import (
        TransformerBlockStack)
    from veles.znicz_tpu.ops.conv import ConvBase
    from veles.znicz_tpu.ops.deconv import Deconv, Depooling
    from veles.znicz_tpu.ops.embedding import EmbeddingForward
    from veles.znicz_tpu.ops.layernorm import LayerNormForward
    from veles.znicz_tpu.ops.pooling import (
        PoolingBase, StochasticPooling)
    from veles.znicz_tpu.ops.normalization import LRNormalizerForward
    from veles.znicz_tpu.ops.dropout import DropoutForward
    from veles.znicz_tpu.ops.activation import ActivationForward

    type_name = getattr(type(unit), "MAPPING", None)
    if type_name not in ENGINE_TYPES:
        raise ValueError(
            "cannot export unit %s (%s, type %r): no C++ engine "
            "counterpart" % (unit.name, type(unit).__name__, type_name))
    spec = {"type": type_name, "name": unit.name, "config": {}}
    if isinstance(unit, All2AllBase):
        spec["config"]["neurons"] = int(unit.neurons)
        spec["config"]["output_sample_shape"] = \
            list(unit.output_sample_shape)
        spec["weights_transposed"] = bool(unit.weights_transposed)
        _export_weighted(unit, path, spec)
    elif isinstance(unit, ConvBase):
        spec["config"].update({
            "n_kernels": int(unit.n_kernels),
            "kx": int(unit.kx), "ky": int(unit.ky),
            "sliding": list(unit.sliding),
            "padding": list(unit.padding),
        })
        _export_weighted(unit, path, spec)
    elif isinstance(unit, Deconv):
        spec["config"].update({
            "n_kernels": int(unit.n_kernels),
            "kx": int(unit.kx), "ky": int(unit.ky),
            "sliding": list(unit.sliding),
            "padding": list(unit.padding),
            # the resolved output geometry (output_shape_source pins
            # it at initialize time; the engine cannot re-derive it)
            "out_shape": [int(d) for d in unit._oshape[1:]],
        })
        _save_extra(unit, path, spec, "weights")
    elif isinstance(unit, Depooling):
        spec["config"].update({
            "kx": int(unit.kx), "ky": int(unit.ky),
            "sliding": list(unit.sliding),
            "out_shape": [int(d) for d in unit._oshape[1:]],
        })
    elif isinstance(unit, StochasticPooling):
        raise ValueError(
            "%s: stochastic pooling has no deterministic inference "
            "form in the C++ engine" % unit.name)
    elif isinstance(unit, PoolingBase):
        spec["config"].update({
            "kx": int(unit.kx), "ky": int(unit.ky),
            "sliding": list(unit.sliding),
        })
    elif isinstance(unit, LRNormalizerForward):
        spec["config"].update({
            "alpha": float(unit.alpha), "beta": float(unit.beta),
            "n": int(unit.n), "k": float(unit.k),
        })
    elif isinstance(unit, EmbeddingForward):
        spec["config"].update({"vocab_size": int(unit.vocab_size),
                               "dim": int(unit.dim)})
        _export_weighted(unit, path, spec)
        if unit._positions is not None:
            # export an EXTENDED sinusoidal table (deterministic, data
            # free) so the C++ --generate decode can grow sequences
            # well past the training seq_len before it must window
            from veles.znicz_tpu.ops.embedding import (
                sinusoidal_positions)
            n = max(4 * unit._positions.shape[0], 256)
            fname = _npy_name(unit, "positions")
            numpy.save(os.path.join(path, fname),
                       sinusoidal_positions(n, unit.dim))
            spec["positions"] = fname
    elif isinstance(unit, LayerNormForward):
        spec["config"]["eps"] = float(unit.eps)
        _export_weighted(unit, path, spec)
    elif isinstance(unit, MultiHeadAttention):
        spec["config"].update({
            "heads": int(unit.heads), "causal": bool(unit.causal),
            "residual": bool(unit.residual),
            "include_bias": bool(unit.include_bias)})
        _export_weighted(unit, path, spec)
        _save_extra(unit, path, spec, "weights_out")
        _save_extra(unit, path, spec, "bias_out",
                    required=unit.include_bias)
    elif isinstance(unit, TransformerFFN):
        spec["config"].update({"hidden": int(unit.hidden),
                               "residual": bool(unit.residual)})
        _export_weighted(unit, path, spec)
        _save_extra(unit, path, spec, "weights2")
        _save_extra(unit, path, spec, "bias2")
    elif isinstance(unit, MoEFFN):
        spec["config"].update({
            "experts": int(unit.experts), "hidden": int(unit.hidden),
            "residual": bool(unit.residual),
            "capacity_factor": float(unit.capacity_factor)})
        _export_weighted(unit, path, spec)
        for extra in ("weights2", "bias2", "router"):
            _save_extra(unit, path, spec, extra)
    elif isinstance(unit, TransformerBlockStack):
        spec["config"].update({
            "layers": int(unit.layers), "heads": int(unit.heads),
            "hidden": int(unit.hidden), "causal": bool(unit.causal),
            "eps": float(unit.eps)})
        for pname in unit.PARAMS:
            _save_extra(unit, path, spec, pname)
    elif isinstance(unit, TokenDenseBase):
        spec["config"]["output_features"] = int(unit.output_features)
        _export_weighted(unit, path, spec)
    elif isinstance(unit, (DropoutForward, ActivationForward)):
        pass  # config-free (dropout is identity at inference)
    else:
        raise ValueError(
            "cannot export unit %s (%s): no C++ engine counterpart"
            % (unit.name, type(unit).__name__))
    return spec


def export_inference(workflow, path, at_valid=False, sync=True):
    """Write the inference archive for ``workflow`` into directory
    ``path`` (created if missing). Device-resident params are synced to
    host first; ``at_valid=True`` exports the epoch-entry view the
    validation metric was measured on (what an improved-gated snapshot
    saves). Pass ``sync=False`` when the caller just synced the same
    view (the snapshotter's export-on-snapshot path)."""
    os.makedirs(path, exist_ok=True)
    step = getattr(workflow, "xla_step", None)
    if sync and step is not None:
        step.sync_host(at_valid=at_valid)
    units = [_unit_spec(u, path) for u in workflow.forwards]
    doc = {
        "format": 1,
        "workflow": workflow.name,
        "input_sample_shape": list(
            workflow.loader.minibatch_data.shape[1:])
        if getattr(workflow, "loader", None) is not None else None,
        "units": units,
    }
    out = os.path.join(path, "contents.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    return out
