"""Master server — the wire side of the elastic-DP compat path.

Re-design of ``veles/server.py`` [U] (SURVEY.md §2.2 "Master server",
§3.3). The reference ran ZeroMQ ROUTER + Twisted; the hot path of the
TPU rebuild is compiled collectives, so this layer only has to carry
the *elastic* story (slaves joining/dying mid-run, master-owned weight
averaging) and tests' master↔slave round-trips. Plain TCP with
length-prefixed pickle frames is sufficient and dependency-free.

Protocol (client-initiated, synchronous per connection):

* ``("hello", name)``            → ``("welcome", slave_id)``
* ``("job", slave_id)``          → ``("job", payload)`` |
                                   ``("wait",)`` | ``("bye",)``
* ``("update", slave_id, data)`` → ``("ok",)``

``payload`` is the per-unit dict from
:class:`veles.distributable.DistributionRegistry` (loader ships
minibatch index ranges, GD units ship weights). A dead slave's
in-flight jobs are re-queued (``drop_slave``, SURVEY.md §5.3).
"""

import hashlib
import hmac
import os
import pickle
import socket
import socketserver
import struct
import threading

from veles.distributable import DistributionRegistry
from veles.logger import Logger

#: SECURITY: frames are pickled Python objects — deserializing one is
#: arbitrary code execution, so every frame carries an HMAC-SHA256 tag
#: keyed on a cluster-shared secret and recv_frame REFUSES to unpickle
#: anything unauthenticated. The secret comes from
#: ``$VELES_CLUSTER_SECRET``; without it set, only loopback operation
#: is allowed (see require_secret_for) — the dev fallback key is
#: public knowledge and protects against accidents, not attackers.
_SECRET = None

_LOOPBACK = ("127.0.0.1", "localhost", "::1")


def _secret():
    global _SECRET
    if _SECRET is None:
        _SECRET = os.environ.get(
            "VELES_CLUSTER_SECRET", "veles-znicz-tpu-dev").encode()
    return _SECRET


def require_secret_for(host, role):
    """Fail closed: refuse non-loopback master/slave endpoints unless
    an explicit cluster secret is configured."""
    if host in _LOOPBACK:
        return
    if "VELES_CLUSTER_SECRET" not in os.environ:
        raise RuntimeError(
            "%s endpoint %r is not loopback and VELES_CLUSTER_SECRET "
            "is unset: the wire protocol deserializes pickle and the "
            "default HMAC key is public. Set VELES_CLUSTER_SECRET to "
            "the same random value on every node." % (role, host))


def send_frame(sock, obj):
    blob = pickle.dumps(obj, protocol=4)
    tag = hmac.new(_secret(), blob, hashlib.sha256).digest()
    sock.sendall(struct.pack(">I", len(blob)) + tag + blob)


#: The length header arrives BEFORE authentication, so it must not be
#: able to command huge allocations: cap it well above any real payload
#: (largest frames ship full model weights) but far below OOM territory.
MAX_FRAME_BYTES = 1 << 30


def recv_frame(sock):
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    size, = struct.unpack(">I", header)
    if size > MAX_FRAME_BYTES:
        raise ConnectionError(
            "frame header claims %d bytes (cap %d) — dropping peer"
            % (size, MAX_FRAME_BYTES))
    tag = _recv_exact(sock, 32)
    if tag is None:
        return None
    blob = _recv_exact(sock, size)
    if blob is None:
        return None
    if not hmac.compare_digest(
            tag, hmac.new(_secret(), blob, hashlib.sha256).digest()):
        raise ConnectionError(
            "frame failed HMAC authentication (cluster secret mismatch "
            "or untrusted peer) — refusing to deserialize")
    return pickle.loads(blob)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def framed_server(address, handle_request, done_event, on_drop,
                  timeout=None):
    """The framed request loop shared by the training master and the
    GA task master (``veles/genetics.py``): a ``ThreadingTCPServer``
    whose per-connection handler pumps HMAC frames through
    ``handle_request`` until ``done_event``, captures the slave id
    from the hello exchange, and calls ``on_drop(slave_id)`` when the
    connection dies — the drop->requeue elasticity hook. ``timeout``
    (seconds) bounds a silent peer: a slave whose host vanishes
    without FIN/RST would otherwise block its handler thread forever
    and strand its in-flight work. The caller owns shutdown +
    server_close (use ``with``)."""

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            if timeout:
                self.request.settimeout(timeout)
            slave_id = None
            try:
                while not done_event.is_set():
                    req = recv_frame(self.request)
                    if req is None:
                        break
                    resp = handle_request(req)
                    if req[0] == "hello":
                        slave_id = resp[1]
                    send_frame(self.request, resp)
                    if resp[0] == "bye":
                        break
            except (ConnectionError, OSError):
                pass               # socket.timeout is an OSError too
            finally:
                if slave_id is not None:
                    on_drop(slave_id)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server(address, Handler)


class MasterServer(Logger):
    """Owns canonical weights + the job queue; never computes."""

    def __init__(self, workflow, address, max_epochs=None):
        self.name = "MasterServer"
        self.workflow = workflow
        host, _, port = str(address).rpartition(":")
        self.address = (host or "0.0.0.0", int(port))
        require_secret_for(self.address[0], "master listen")
        self.registry = DistributionRegistry(workflow)
        self.lock = threading.RLock()
        self.slaves = {}
        self._next_slave = 1
        self.epoch = 0
        if max_epochs is None:
            max_epochs = getattr(
                getattr(workflow, "decision", None), "max_epochs", None)
        if max_epochs is None:
            # the master never runs the decision unit, so patience-only
            # stopping cannot work here — demand an explicit bound
            raise ValueError(
                "MasterServer needs max_epochs (decision.max_epochs is "
                "None; early-stopping-only configs cannot drive a "
                "master)")
        self.max_epochs = int(max_epochs)
        self.done = threading.Event()
        self._server = None
        loader = workflow.loader
        loader.master_start_epoch()

    # -- job lifecycle -------------------------------------------------

    def handle(self, request):
        kind = request[0]
        with self.lock:
            if kind == "hello":
                slave_id = self._next_slave
                self._next_slave += 1
                self.slaves[slave_id] = {"name": request[1], "jobs": 0}
                self.info("slave %d (%s) joined", slave_id, request[1])
                return ("welcome", slave_id)
            if kind == "job":
                if self.done.is_set():
                    return ("bye",)
                # cheap emptiness check BEFORE serializing weight
                # payloads — idle slaves poll here every 20ms
                if not self.workflow.loader._pending_jobs:
                    self._advance_epoch()
                    if self.done.is_set():
                        return ("bye",)
                    return ("wait",)
                job = self.registry.generate_job(request[1])
                if job.get(self.workflow.loader.name) is None:
                    return ("wait",)
                self.slaves[request[1]]["jobs"] += 1
                return ("job", job)
            if kind == "update":
                self.registry.apply_update(request[2], request[1])
                return ("ok",)
        return ("error", "unknown request %r" % (kind,))

    def _advance_epoch(self):
        loader = self.workflow.loader
        if loader._pending_jobs or any(loader._inflight.values()):
            return
        self.epoch += 1
        if self.epoch >= self.max_epochs:
            self.done.set()
            return
        loader.master_start_epoch()

    def drop_slave(self, slave_id):
        with self.lock:
            if slave_id in self.slaves:
                self.info("slave %d dropped; requeueing", slave_id)
                self.registry.drop_slave(slave_id)
                del self.slaves[slave_id]

    def status(self):
        """Cluster topology snapshot for the dashboard (SURVEY.md
        §5.5): connected slaves with their served-job counts, plus
        master progress."""
        with self.lock:
            return {
                "mode": "master",
                "epoch": self.epoch,
                "max_epochs": self.max_epochs,
                "complete": self.done.is_set(),
                "n_slaves": len(self.slaves),
                "slaves": {
                    str(sid): dict(info)
                    for sid, info in self.slaves.items()},
            }

    # -- socket plumbing ----------------------------------------------

    def serve_forever(self, poll=0.05):
        with framed_server(self.address, self.handle, self.done,
                           self.drop_slave) as server:
            self._server = server
            self.bound_address = server.server_address
            threading.Thread(target=server.serve_forever,
                             args=(poll,), daemon=True).start()
            self.done.wait()
            server.shutdown()
        return self

    def start_background(self):
        """Serve on a daemon thread (tests, co-located master)."""
        import time
        thread = threading.Thread(target=self.serve_forever, daemon=True)
        thread.start()
        for _ in range(500):
            if hasattr(self, "bound_address"):
                return thread
            if not thread.is_alive():
                break
            time.sleep(0.01)
        raise RuntimeError("master server failed to start")
